"""Paged KV-cache: an explicit, mesh-sharded pytree + host block ledger.

Device side, the cache is a :class:`KVCache` NamedTuple (automatically a
JAX pytree) of fixed-shape arrays — jit-stable across the whole serving
run:

- ``k``/``v``: ``[L, max_batch, num_blocks, block_size, kv_heads,
  head_dim]`` — every layer, every decode *slot*, the slot's block ring.
  GQA-aware: K/V are stored at ``kv_heads`` width (never broadcast to
  ``num_heads``).  Sharded per the :class:`~dlbb_tpu.parallel.plan.
  ParallelismPlan`: the slot (batch) dim over ``dp``, the kv-head dim
  over ``tp`` — the same Megatron split the QKV projection produces, so
  cache writes and decode reads are shard-local and the audit's byte
  ceiling can prove no step ever re-gathers the cache
  (``docs/serving.md``).
- ``lengths``: ``[max_batch] int32``, tokens currently valid per slot —
  replicated (tiny; every shard needs it to build attention masks).

Writes are pure masked selects (one-hot over the slot / flat-position
dim), never gather/scatter with cross-shard indices — elementwise ops
GSPMD partitions without inserting a single collective.  XLA turns them
into in-place updates because every step donates the cache.

Host side, :class:`BlockLedger` does the alloc/free/append accounting
against a global block budget: admission *reserves* a request's
worst-case blocks (``ceil((prompt+output)/block_size)``) so a trace can
never OOM the cache mid-run (the build-time HBM gate is
``models.configs.validate_serving``), appends track blocks actually
holding tokens (the occupancy the report plots), and completion frees
both.  The ledger raising on over-use is a *bug* invariant, not a load
condition — reservation-based admission makes it unreachable.

Two capacity levers layer on top (``docs/serving.md``, "Prefix cache &
quantized KV"):

- **Shared-prefix blocks** (``serving.prefix_caching``): full prompt
  blocks are content-addressed by their token-id chain in a host-side
  :class:`PrefixTrie` inside the ledger.  A trie node is one *logical*
  block, charged ONCE against the pool no matter how many resident
  slots hold a physical copy; its refcount is the set of those slots,
  so a block is only returned to the pool when the last reader frees
  (`free` can never tear a live reader).  A request whose prompt
  matches an indexed chain attaches to the shared blocks and prefills
  only the suffix; the blocks past the attach point that the trie also
  matched are rewritten privately — the copy-on-write on first
  divergent append, counted in ``cow_blocks``.  Trie + refcounts
  snapshot/restore WITH the ledger, so a dispatch rollback can never
  double-free or leak a shared block.
- **int8 KV planes** (``serving.kv_quantization="int8"``):
  :class:`QuantKVCache` stores K/V as int8 blocks plus per-block
  per-kv-head fp32 scales as a side-channel plane (the symmetric-amax
  codec of ``comm/compression.py``), quartering the cache bytes the
  HBM admission gate prices — ``models.configs.
  kv_cache_bytes_per_device`` knows the layout, and the static memory
  audit's ``serving-cache-drift`` rule pins it to the compiled decode
  carry.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlbb_tpu.models.configs import ModelConfig


class KVCache(NamedTuple):
    """The device half of the paged cache (see module docstring)."""

    k: jax.Array        # [L, max_batch, num_blocks, block_size, kvh, d]
    v: jax.Array        # same
    lengths: jax.Array  # [max_batch] int32

    @property
    def max_batch(self) -> int:
        return self.k.shape[1]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_seq(self) -> int:
        return self.num_blocks * self.block_size


def cache_specs(mesh: Optional[Mesh]) -> KVCache:
    """PartitionSpecs matching :class:`KVCache`'s structure for ``mesh``:
    slot dim over ``dp``, kv-head dim over ``tp`` (each only when the
    mesh has that axis with size > 1); lengths replicated."""
    axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    dp = "dp" if "dp" in axes and mesh.shape["dp"] > 1 else None
    tp = "tp" if "tp" in axes and mesh.shape["tp"] > 1 else None
    kv_spec = P(None, dp, None, None, tp, None)
    return KVCache(k=kv_spec, v=kv_spec, lengths=P(None))


def cache_shardings(mesh: Mesh) -> KVCache:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), cache_specs(mesh),
        is_leaf=lambda x: isinstance(x, P),
    )


def create_kv_cache(
    config: ModelConfig,
    max_batch: int,
    num_blocks: int,
    block_size: int,
    mesh: Optional[Mesh] = None,
) -> KVCache:
    """Zero-initialised cache, created *directly sharded* onto the mesh
    (jit with explicit out-shardings — same trick as
    ``init_params_sharded``: no device ever holds the replicated cache)."""
    from dlbb_tpu.models.transformer import _dtype_of

    dtype = _dtype_of(config.dtype)
    shape = (config.num_layers, max_batch, num_blocks, block_size,
             config.kv_heads, config.head_dim)

    def build() -> KVCache:
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((max_batch,), jnp.int32),
        )

    if mesh is None:
        return build()
    return jax.jit(build, out_shardings=cache_shardings(mesh))()


def gather_cache_slots(cache: KVCache, idx: jax.Array) -> KVCache:
    """Repack the slots named by ``idx`` (``[b'] int32``, b' <
    max_batch) into a smaller cache — the device half of slot
    compaction (``serve/engine.py``).  The slot dim must be UNSHARDED
    (dp=1, enforced by ``ServingConfig.validate``): then the take is a
    purely local gather and the compaction jit lowers to zero
    collectives (audited — ``serve/engine.py::compact[tp]``)."""
    return KVCache(
        k=jnp.take(cache.k, idx, axis=1),
        v=jnp.take(cache.v, idx, axis=1),
        lengths=jnp.take(cache.lengths, idx, axis=0),
    )


def scatter_cache_slots(cache: KVCache, small: KVCache,
                        idx: jax.Array) -> KVCache:
    """Write a compacted cache's rows back into their big-batch slots
    (inverse of :func:`gather_cache_slots`; ``idx`` rows must be
    distinct — the engine pads the active-slot list with distinct FREE
    slots, never duplicates, so the scatter is well-defined)."""
    return KVCache(
        k=cache.k.at[:, idx].set(small.k),
        v=cache.v.at[:, idx].set(small.v),
        lengths=cache.lengths.at[idx].set(small.lengths),
    )


# ---------------------------------------------------------------------------
# int8-quantized cache plane (serving.kv_quantization="int8")
# ---------------------------------------------------------------------------

KV_QMAX = 127.0  # symmetric int8, same codec as comm/compression.py


class QuantKVCache(NamedTuple):
    """The int8 variant of :class:`KVCache`: K/V blocks stored as int8
    with per-block per-kv-head fp32 scales as a side-channel plane.
    Scales shard exactly like the data they scale (slot dim over dp,
    kv-head dim over tp), so dequantisation inside the decode step is an
    elementwise broadcast — shard-local, zero collectives."""

    k: jax.Array         # int8 [L, max_batch, num_blocks, block_size, kvh, d]
    v: jax.Array         # same
    k_scale: jax.Array   # f32  [L, max_batch, num_blocks, kvh]
    v_scale: jax.Array   # same
    lengths: jax.Array   # [max_batch] int32

    @property
    def max_batch(self) -> int:
        return self.k.shape[1]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_seq(self) -> int:
        return self.num_blocks * self.block_size


def quant_cache_specs(mesh: Optional[Mesh]) -> QuantKVCache:
    """PartitionSpecs for :class:`QuantKVCache`: data like
    :func:`cache_specs`, scales dropping the in-block dims."""
    axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    dp = "dp" if "dp" in axes and mesh.shape["dp"] > 1 else None
    tp = "tp" if "tp" in axes and mesh.shape["tp"] > 1 else None
    kv_spec = P(None, dp, None, None, tp, None)
    sc_spec = P(None, dp, None, tp)
    return QuantKVCache(k=kv_spec, v=kv_spec, k_scale=sc_spec,
                        v_scale=sc_spec, lengths=P(None))


def quant_cache_shardings(mesh: Mesh) -> QuantKVCache:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), quant_cache_specs(mesh),
        is_leaf=lambda x: isinstance(x, P),
    )


def create_quant_kv_cache(
    config: ModelConfig,
    max_batch: int,
    num_blocks: int,
    block_size: int,
    mesh: Optional[Mesh] = None,
) -> QuantKVCache:
    """Zero-initialised int8 cache (scales start at 1.0 so an untouched
    block dequantises to exact zeros), created directly sharded."""
    shape = (config.num_layers, max_batch, num_blocks, block_size,
             config.kv_heads, config.head_dim)
    sc_shape = (config.num_layers, max_batch, num_blocks, config.kv_heads)

    def build() -> QuantKVCache:
        return QuantKVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.ones(sc_shape, jnp.float32),
            v_scale=jnp.ones(sc_shape, jnp.float32),
            lengths=jnp.zeros((max_batch,), jnp.int32),
        )

    if mesh is None:
        return build()
    return jax.jit(build, out_shardings=quant_cache_shardings(mesh))()


def quantize_kv_blocks(blocks: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 over paged K/V blocks ``[..., block_size, kvh,
    head_dim]`` with one fp32 scale per (block, kv-head): ``scale =
    amax / 127`` guarded to 1.0 on all-zero blocks (the
    ``comm/compression.py`` idiom).  Returns ``(int8 blocks, f32 scales
    [..., kvh])``.  The round-trip is bit-stable: requantising a
    dequantised block reproduces the int8 codes exactly (|q·s/s − q| <
    2⁻²²·127 ≪ 0.5), so rewriting a whole cache layer never drifts the
    blocks that were not touched."""
    a = jnp.max(jnp.abs(blocks.astype(jnp.float32)), axis=(-3, -1))
    s = jnp.where(a > 0.0, a / KV_QMAX, 1.0)
    q = jnp.clip(jnp.round(blocks.astype(jnp.float32) / s[..., None, :, None]),
                 -KV_QMAX, KV_QMAX)
    return q.astype(jnp.int8), s.astype(jnp.float32)


def dequantize_kv_blocks(q: jax.Array, scales: jax.Array,
                         dtype: jnp.dtype) -> jax.Array:
    """Inverse of :func:`quantize_kv_blocks` (broadcast multiply —
    elementwise, shard-local under the cache sharding contract)."""
    return (q.astype(jnp.float32) * scales[..., None, :, None]).astype(dtype)


class CacheOverflow(RuntimeError):
    """A slot used more blocks than were reserved for it — an engine bug
    (reservation-based admission makes this unreachable under load)."""


class PrefixTrie:
    """Host-side radix index over full-block token-id chains.

    One node per *logical* full block, keyed by the tuple of token ids
    it holds under its parent chain — content-addressing, so identical
    prompts dedupe even across trace groups.  A node's refcount is the
    set of slots physically holding that block content; a slot always
    holds a contiguous prefix of its chain starting at the root, so the
    refs at any matched node are valid donors for the WHOLE path above
    it (child refs ⊆ parent refs), and a node with an empty refcount
    has no live reader and is pruned.  Entirely host-side dict walking
    — the device programs never see it (``host-transfer-in-loop``
    stays clean)."""

    def __init__(self) -> None:
        # (parent_node, block token tuple) -> node id; root is node 0
        self._children: dict[tuple[int, tuple[int, ...]], int] = {}
        self._parent: dict[int, tuple[int, tuple[int, ...]]] = {}
        self._refs: dict[int, set[int]] = {}        # node -> holder slots
        self._slot_nodes: dict[int, list[int]] = {}  # slot -> chain nodes
        self._next_id = 1

    @property
    def num_nodes(self) -> int:
        """Logical shared blocks currently indexed (the pool charge)."""
        return len(self._refs)

    def total_refs(self) -> int:
        return sum(len(r) for r in self._refs.values())

    def shared_depth(self, slot: int) -> int:
        return len(self._slot_nodes.get(slot, ()))

    def match(self, chain: list[tuple[int, ...]]) -> tuple[int, Optional[int]]:
        """Longest indexed prefix of ``chain``: returns ``(blocks
        matched, donor slot)`` — the donor (lowest resident slot id, for
        determinism) physically holds every matched block."""
        node, depth, donors = 0, 0, None
        for key in chain:
            child = self._children.get((node, tuple(key)))
            if child is None:
                break
            node, depth, donors = child, depth + 1, self._refs[child]
        if depth == 0 or not donors:
            return 0, None
        return depth, min(donors)

    def attach(self, slot: int, chain: list[tuple[int, ...]],
               depth: int) -> None:
        """Record ``slot`` as a resident holder of the first ``depth``
        blocks of ``chain`` (which must already be indexed — callers
        attach only what :meth:`match` returned)."""
        if slot in self._slot_nodes:
            raise CacheOverflow(f"slot {slot} already holds a chain")
        node, nodes = 0, []
        for key in chain[:depth]:
            node = self._children[(node, tuple(key))]
            self._refs[node].add(slot)
            nodes.append(node)
        self._slot_nodes[slot] = nodes

    def extend(self, slot: int, chain: list[tuple[int, ...]]) -> tuple[int, int]:
        """Index ``slot``'s full chain past what it already holds,
        creating nodes as needed.  Returns ``(created, newly_ref)``:
        ``created`` nodes are new logical pool blocks; ``newly_ref``
        counts every block that moved from the slot's private
        reservation into shared accounting (``created`` ⊆ it — an
        existing node newly ref'd is a dedupe, freeing one block of
        budget)."""
        nodes = self._slot_nodes.setdefault(slot, [])
        node = nodes[-1] if nodes else 0
        created = newly = 0
        for key in chain[len(nodes):]:
            key = tuple(key)
            child = self._children.get((node, key))
            if child is None:
                child = self._next_id
                self._next_id += 1
                self._children[(node, key)] = child
                self._parent[child] = (node, key)
                self._refs[child] = set()
                created += 1
            if slot not in self._refs[child]:
                self._refs[child].add(slot)
                newly += 1
            nodes.append(child)
            node = child
        return created, newly

    def release(self, slot: int) -> int:
        """Drop ``slot``'s residency; prune (deepest-first) every node
        no live slot still holds.  Returns the pruned count — the
        logical blocks actually returned to the pool; blocks other
        slots still read stay charged, so eviction never tears a live
        reader."""
        pruned = 0
        for node in reversed(self._slot_nodes.pop(slot, [])):
            refs = self._refs.get(node)
            if refs is None:
                continue
            refs.discard(slot)
            if not refs:
                parent, key = self._parent.pop(node)
                del self._children[(parent, key)]
                del self._refs[node]
                pruned += 1
        return pruned

    def snapshot(self) -> dict:
        return {
            "children": dict(self._children),
            "parent": dict(self._parent),
            "refs": {n: set(r) for n, r in self._refs.items()},
            "slot_nodes": {s: list(n)
                           for s, n in self._slot_nodes.items()},
            "next_id": self._next_id,
        }

    def restore(self, snap: dict) -> None:
        self._children = dict(snap["children"])
        self._parent = dict(snap["parent"])
        self._refs = {n: set(r) for n, r in snap["refs"].items()}
        self._slot_nodes = {s: list(n)
                            for s, n in snap["slot_nodes"].items()}
        self._next_id = snap["next_id"]


class BlockLedger:
    """Host-side alloc/free/append accounting for the block pool.

    ``total_blocks`` is the global budget (defaults to the physical pool,
    ``max_batch * num_blocks``; configurable lower to model cache
    pressure).  Reservation is all-or-nothing per request; ``append``
    moves a block from reserved to in-use when a token crosses a block
    boundary; ``free`` returns everything.

    With ``prefix_caching`` the ledger carries a :class:`PrefixTrie`:
    every trie node is a logical block charged ONCE to the pool
    (``blocks_reserved`` = private reservations + trie nodes), a slot's
    private reservation shrinks by the blocks it shares, and ``free``
    returns a shared block only when the trie prunes it (refcount hit
    zero)."""

    def __init__(self, total_blocks: int, block_size: int,
                 prefix_caching: bool = False) -> None:
        if total_blocks < 1 or block_size < 1:
            raise ValueError(
                f"ledger needs positive sizes (total_blocks="
                f"{total_blocks}, block_size={block_size})"
            )
        self.total_blocks = total_blocks
        self.block_size = block_size
        self._reserved: dict[int, int] = {}   # slot -> PRIVATE blocks
        self._tokens: dict[int, int] = {}     # slot -> tokens appended
        self._shared: dict[int, int] = {}     # slot -> shared blocks held
        self.trie: Optional[PrefixTrie] = (
            PrefixTrie() if prefix_caching else None)
        self.cow_blocks = 0   # copy-on-write rewrites (monotone)
        self.peak_reserved = 0
        self.peak_in_use = 0
        self.peak_shared = 0

    def blocks_for(self, tokens: int) -> int:
        return max(1, math.ceil(tokens / self.block_size))

    @property
    def shared_blocks(self) -> int:
        """Logical blocks in the shared pool (one per trie node)."""
        return self.trie.num_nodes if self.trie is not None else 0

    @property
    def blocks_reserved(self) -> int:
        return sum(self._reserved.values()) + self.shared_blocks

    @property
    def blocks_in_use(self) -> int:
        private = sum(
            max(0, self.blocks_for(t) - self._shared.get(s, 0)) if t else 0
            for s, t in self._tokens.items())
        return private + self.shared_blocks

    @property
    def blocks_free(self) -> int:
        return self.total_blocks - self.blocks_reserved

    def can_reserve(self, total_tokens: int,
                    shared_blocks: int = 0) -> bool:
        need = max(0, self.blocks_for(total_tokens) - shared_blocks)
        return need <= self.blocks_free

    def match_prefix(self, chain: list[tuple[int, ...]]
                     ) -> tuple[int, Optional[int]]:
        """Longest indexed block-chain prefix → ``(blocks, donor slot)``
        (``(0, None)`` when prefix caching is off or nothing matches)."""
        if self.trie is None or not chain:
            return 0, None
        return self.trie.match(chain)

    def reserve(self, slot: int, total_tokens: int,
                chain: Optional[list[tuple[int, ...]]] = None,
                attach_blocks: int = 0) -> int:
        """Reserve a request's worst-case blocks for ``slot``; returns
        the PRIVATE count.  With ``attach_blocks`` > 0 the slot also
        becomes a refcounted holder of the first ``attach_blocks``
        blocks of ``chain`` (already charged to the shared pool), so
        only the remainder is drawn from the free budget.  Raises when
        the slot is already occupied or the budget cannot cover it
        (callers gate on :meth:`can_reserve`)."""
        if slot in self._reserved:
            raise CacheOverflow(f"slot {slot} already holds a reservation")
        if attach_blocks and self.trie is None:
            raise CacheOverflow("attach requires prefix_caching")
        need = max(0, self.blocks_for(total_tokens) - attach_blocks)
        if need > self.blocks_free:
            raise CacheOverflow(
                f"cannot reserve {need} blocks for slot {slot}: only "
                f"{self.blocks_free}/{self.total_blocks} free"
            )
        if attach_blocks:
            self.trie.attach(slot, chain, attach_blocks)
        self._reserved[slot] = need
        self._tokens[slot] = 0
        self._shared[slot] = attach_blocks
        self.peak_reserved = max(self.peak_reserved, self.blocks_reserved)
        self.peak_shared = max(self.peak_shared, self.shared_blocks)
        return need

    def register(self, slot: int, chain: list[tuple[int, ...]]) -> int:
        """Index ``slot``'s full prompt block-chain in the trie (after
        its prefill completed, so the slot physically holds every
        block).  Blocks newly shared move from the slot's private
        reservation into the pool charge; an already-indexed block this
        slot now also holds is a dedupe that *frees* budget.  Returns
        the number of blocks that moved to shared accounting."""
        if self.trie is None or not chain:
            return 0
        if slot not in self._reserved:
            raise CacheOverflow(f"register of unreserved slot {slot}")
        _, newly = self.trie.extend(slot, chain)
        if newly > self._reserved[slot]:
            raise CacheOverflow(
                f"slot {slot} shared {newly} blocks beyond its private "
                f"reservation of {self._reserved[slot]}"
            )
        self._reserved[slot] -= newly
        self._shared[slot] = self._shared.get(slot, 0) + newly
        self.peak_shared = max(self.peak_shared, self.shared_blocks)
        return newly

    def note_cow(self, blocks: int) -> None:
        """Count copy-on-write block rewrites (the trie matched deeper
        than the request could attach, so the divergent tail is
        recomputed into private blocks).  Monotone, like the peaks."""
        self.cow_blocks += blocks

    def append(self, slot: int, tokens: int = 1) -> None:
        """Account ``tokens`` written into ``slot`` (prefill passes the
        prompt length, decode passes 1)."""
        if slot not in self._reserved:
            raise CacheOverflow(f"append to unreserved slot {slot}")
        self._tokens[slot] += tokens
        entitled = self._reserved[slot] + self._shared.get(slot, 0)
        if self.blocks_for(self._tokens[slot]) > entitled:
            raise CacheOverflow(
                f"slot {slot} outgrew its reservation "
                f"({self._tokens[slot]} tokens > "
                f"{entitled} blocks x {self.block_size})"
            )
        self.peak_in_use = max(self.peak_in_use, self.blocks_in_use)

    def free(self, slot: int) -> int:
        """Release a slot's reservation; returns the blocks actually
        returned to the pool: its private blocks plus every shared
        block whose refcount dropped to zero (blocks other live slots
        still read stay charged — no torn readers, no double-free)."""
        if slot not in self._reserved:
            raise CacheOverflow(f"free of unreserved slot {slot}")
        blocks = self._reserved.pop(slot)
        self._tokens.pop(slot)
        self._shared.pop(slot, None)
        if self.trie is not None:
            blocks += self.trie.release(slot)
        return blocks

    def snapshot(self) -> dict:
        """Copy of the alloc/append accounting — the serving engine's
        pre-dispatch rollback point (``docs/resilience.md``): a failed
        or torn decode unit restores this before re-issuing.  Includes
        the trie + refcounts, so a retry can never double-free or leak
        a shared block."""
        return {"reserved": dict(self._reserved),
                "tokens": dict(self._tokens),
                "shared": dict(self._shared),
                "trie": (self.trie.snapshot()
                         if self.trie is not None else None)}

    def restore(self, snap: dict) -> None:
        """Roll the accounting back to a :meth:`snapshot`.  The peak
        counters deliberately stay monotone (a rolled-back peak was
        still a real high-water mark of host bookkeeping)."""
        self._reserved.clear()
        self._reserved.update(snap["reserved"])
        self._tokens.clear()
        self._tokens.update(snap["tokens"])
        self._shared.clear()
        self._shared.update(snap.get("shared", {}))
        if self.trie is not None and snap.get("trie") is not None:
            self.trie.restore(snap["trie"])

    def stats(self) -> dict[str, int]:
        return {
            "total_blocks": self.total_blocks,
            "blocks_reserved": self.blocks_reserved,
            "blocks_in_use": self.blocks_in_use,
            "peak_blocks_reserved": self.peak_reserved,
            "peak_blocks_in_use": self.peak_in_use,
            "shared_blocks": self.shared_blocks,
            "peak_shared_blocks": self.peak_shared,
            "prefix_refs": (self.trie.total_refs()
                            if self.trie is not None else 0),
            "cow_blocks": self.cow_blocks,
        }
