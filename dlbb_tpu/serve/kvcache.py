"""Paged KV-cache: an explicit, mesh-sharded pytree + host block ledger.

Device side, the cache is a :class:`KVCache` NamedTuple (automatically a
JAX pytree) of fixed-shape arrays — jit-stable across the whole serving
run:

- ``k``/``v``: ``[L, max_batch, num_blocks, block_size, kv_heads,
  head_dim]`` — every layer, every decode *slot*, the slot's block ring.
  GQA-aware: K/V are stored at ``kv_heads`` width (never broadcast to
  ``num_heads``).  Sharded per the :class:`~dlbb_tpu.parallel.plan.
  ParallelismPlan`: the slot (batch) dim over ``dp``, the kv-head dim
  over ``tp`` — the same Megatron split the QKV projection produces, so
  cache writes and decode reads are shard-local and the audit's byte
  ceiling can prove no step ever re-gathers the cache
  (``docs/serving.md``).
- ``lengths``: ``[max_batch] int32``, tokens currently valid per slot —
  replicated (tiny; every shard needs it to build attention masks).

Writes are pure masked selects (one-hot over the slot / flat-position
dim), never gather/scatter with cross-shard indices — elementwise ops
GSPMD partitions without inserting a single collective.  XLA turns them
into in-place updates because every step donates the cache.

Host side, :class:`BlockLedger` does the alloc/free/append accounting
against a global block budget: admission *reserves* a request's
worst-case blocks (``ceil((prompt+output)/block_size)``) so a trace can
never OOM the cache mid-run (the build-time HBM gate is
``models.configs.validate_serving``), appends track blocks actually
holding tokens (the occupancy the report plots), and completion frees
both.  The ledger raising on over-use is a *bug* invariant, not a load
condition — reservation-based admission makes it unreachable.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlbb_tpu.models.configs import ModelConfig


class KVCache(NamedTuple):
    """The device half of the paged cache (see module docstring)."""

    k: jax.Array        # [L, max_batch, num_blocks, block_size, kvh, d]
    v: jax.Array        # same
    lengths: jax.Array  # [max_batch] int32

    @property
    def max_batch(self) -> int:
        return self.k.shape[1]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_seq(self) -> int:
        return self.num_blocks * self.block_size


def cache_specs(mesh: Optional[Mesh]) -> KVCache:
    """PartitionSpecs matching :class:`KVCache`'s structure for ``mesh``:
    slot dim over ``dp``, kv-head dim over ``tp`` (each only when the
    mesh has that axis with size > 1); lengths replicated."""
    axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    dp = "dp" if "dp" in axes and mesh.shape["dp"] > 1 else None
    tp = "tp" if "tp" in axes and mesh.shape["tp"] > 1 else None
    kv_spec = P(None, dp, None, None, tp, None)
    return KVCache(k=kv_spec, v=kv_spec, lengths=P(None))


def cache_shardings(mesh: Mesh) -> KVCache:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), cache_specs(mesh),
        is_leaf=lambda x: isinstance(x, P),
    )


def create_kv_cache(
    config: ModelConfig,
    max_batch: int,
    num_blocks: int,
    block_size: int,
    mesh: Optional[Mesh] = None,
) -> KVCache:
    """Zero-initialised cache, created *directly sharded* onto the mesh
    (jit with explicit out-shardings — same trick as
    ``init_params_sharded``: no device ever holds the replicated cache)."""
    from dlbb_tpu.models.transformer import _dtype_of

    dtype = _dtype_of(config.dtype)
    shape = (config.num_layers, max_batch, num_blocks, block_size,
             config.kv_heads, config.head_dim)

    def build() -> KVCache:
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((max_batch,), jnp.int32),
        )

    if mesh is None:
        return build()
    return jax.jit(build, out_shardings=cache_shardings(mesh))()


def gather_cache_slots(cache: KVCache, idx: jax.Array) -> KVCache:
    """Repack the slots named by ``idx`` (``[b'] int32``, b' <
    max_batch) into a smaller cache — the device half of slot
    compaction (``serve/engine.py``).  The slot dim must be UNSHARDED
    (dp=1, enforced by ``ServingConfig.validate``): then the take is a
    purely local gather and the compaction jit lowers to zero
    collectives (audited — ``serve/engine.py::compact[tp]``)."""
    return KVCache(
        k=jnp.take(cache.k, idx, axis=1),
        v=jnp.take(cache.v, idx, axis=1),
        lengths=jnp.take(cache.lengths, idx, axis=0),
    )


def scatter_cache_slots(cache: KVCache, small: KVCache,
                        idx: jax.Array) -> KVCache:
    """Write a compacted cache's rows back into their big-batch slots
    (inverse of :func:`gather_cache_slots`; ``idx`` rows must be
    distinct — the engine pads the active-slot list with distinct FREE
    slots, never duplicates, so the scatter is well-defined)."""
    return KVCache(
        k=cache.k.at[:, idx].set(small.k),
        v=cache.v.at[:, idx].set(small.v),
        lengths=cache.lengths.at[idx].set(small.lengths),
    )


class CacheOverflow(RuntimeError):
    """A slot used more blocks than were reserved for it — an engine bug
    (reservation-based admission makes this unreachable under load)."""


class BlockLedger:
    """Host-side alloc/free/append accounting for the block pool.

    ``total_blocks`` is the global budget (defaults to the physical pool,
    ``max_batch * num_blocks``; configurable lower to model cache
    pressure).  Reservation is all-or-nothing per request; ``append``
    moves a block from reserved to in-use when a token crosses a block
    boundary; ``free`` returns everything."""

    def __init__(self, total_blocks: int, block_size: int) -> None:
        if total_blocks < 1 or block_size < 1:
            raise ValueError(
                f"ledger needs positive sizes (total_blocks="
                f"{total_blocks}, block_size={block_size})"
            )
        self.total_blocks = total_blocks
        self.block_size = block_size
        self._reserved: dict[int, int] = {}   # slot -> blocks reserved
        self._tokens: dict[int, int] = {}     # slot -> tokens appended
        self.peak_reserved = 0
        self.peak_in_use = 0

    def blocks_for(self, tokens: int) -> int:
        return max(1, math.ceil(tokens / self.block_size))

    @property
    def blocks_reserved(self) -> int:
        return sum(self._reserved.values())

    @property
    def blocks_in_use(self) -> int:
        return sum(self.blocks_for(t) if t else 0
                   for t in self._tokens.values())

    @property
    def blocks_free(self) -> int:
        return self.total_blocks - self.blocks_reserved

    def can_reserve(self, total_tokens: int) -> bool:
        return self.blocks_for(total_tokens) <= self.blocks_free

    def reserve(self, slot: int, total_tokens: int) -> int:
        """Reserve a request's worst-case blocks for ``slot``; returns the
        count.  Raises when the slot is already occupied or the budget
        cannot cover it (callers gate on :meth:`can_reserve`)."""
        if slot in self._reserved:
            raise CacheOverflow(f"slot {slot} already holds a reservation")
        need = self.blocks_for(total_tokens)
        if need > self.blocks_free:
            raise CacheOverflow(
                f"cannot reserve {need} blocks for slot {slot}: only "
                f"{self.blocks_free}/{self.total_blocks} free"
            )
        self._reserved[slot] = need
        self._tokens[slot] = 0
        self.peak_reserved = max(self.peak_reserved, self.blocks_reserved)
        return need

    def append(self, slot: int, tokens: int = 1) -> None:
        """Account ``tokens`` written into ``slot`` (prefill passes the
        prompt length, decode passes 1)."""
        if slot not in self._reserved:
            raise CacheOverflow(f"append to unreserved slot {slot}")
        self._tokens[slot] += tokens
        if self.blocks_for(self._tokens[slot]) > self._reserved[slot]:
            raise CacheOverflow(
                f"slot {slot} outgrew its reservation "
                f"({self._tokens[slot]} tokens > "
                f"{self._reserved[slot]} blocks x {self.block_size})"
            )
        self.peak_in_use = max(self.peak_in_use, self.blocks_in_use)

    def free(self, slot: int) -> int:
        """Release a slot's reservation; returns the blocks returned."""
        if slot not in self._reserved:
            raise CacheOverflow(f"free of unreserved slot {slot}")
        blocks = self._reserved.pop(slot)
        self._tokens.pop(slot)
        return blocks

    def snapshot(self) -> dict[str, dict[int, int]]:
        """Copy of the alloc/append accounting — the serving engine's
        pre-dispatch rollback point (``docs/resilience.md``): a failed
        or torn decode unit restores this before re-issuing."""
        return {"reserved": dict(self._reserved),
                "tokens": dict(self._tokens)}

    def restore(self, snap: dict[str, dict[int, int]]) -> None:
        """Roll the accounting back to a :meth:`snapshot`.  The peak
        counters deliberately stay monotone (a rolled-back peak was
        still a real high-water mark of host bookkeeping)."""
        self._reserved.clear()
        self._reserved.update(snap["reserved"])
        self._tokens.clear()
        self._tokens.update(snap["tokens"])

    def stats(self) -> dict[str, int]:
        return {
            "total_blocks": self.total_blocks,
            "blocks_reserved": self.blocks_reserved,
            "blocks_in_use": self.blocks_in_use,
            "peak_blocks_reserved": self.peak_reserved,
            "peak_blocks_in_use": self.peak_in_use,
        }
