"""Synthetic serving traffic: seeded, replayable request traces.

The serving benchmark (``serve/bench.py``) is trace-driven: a
:class:`TrafficTrace` fixes every request's arrival time, prompt length,
output length, and embedding seed up front, so a run is exactly
reproducible (same trace + same engine config => same admission order,
same rejections, same token counts) and two engine configurations can be
compared on *identical* load.  Three arrival processes model the
"millions of users" regimes the ROADMAP north-star cares about:

- ``poisson``  — homogeneous Poisson arrivals (exponential inter-arrival
  times at ``rate`` req/s): the steady-state baseline.
- ``bursty``   — a 2-state Markov-modulated Poisson process (MMPP):
  exponentially-distributed dwells in a ``calm`` state at ``rate`` and a
  ``burst`` state at ``rate * burst_factor``.  Bursts are what stress
  admission control and the bounded queue.
- ``diurnal``  — a nonhomogeneous Poisson process with a sinusoidal rate
  profile ``rate * (1 + depth * sin(2*pi*t/period))``, sampled by
  Lewis-Shedler thinning: the compressed day/night cycle.

Prompt/output lengths are sampled from clipped lognormals (long-tailed,
like real chat traffic) inside caller-given bounds; each request carries
its own embedding seed for :func:`dlbb_tpu.data.synthetic.
request_embeddings`.  Traces serialise to JSON (schema
``dlbb_serving_trace_v1``, documented in ``docs/serving.md``) through the
repo's atomic writer.
"""

from __future__ import annotations

import json
import math
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Iterator, Optional

import numpy as np

TRACE_SCHEMA = "dlbb_serving_trace_v1"

TRACE_KINDS = ("poisson", "bursty", "diurnal")


@dataclass(frozen=True)
class Request:
    """One serving request, fully determined at trace-generation time.

    arrival_s is relative to the start of the run (the engine's
    monotonic clock); ``seed`` derives the request's synthetic prompt
    embeddings, so replaying a trace replays the exact inputs.
    ``deadline_s`` is an optional per-request SLO, in seconds from
    *arrival*: the scheduler sheds a queued request whose wait has
    already blown it (``request-rejected[reason=deadline]``), and a
    request that completes past it is counted
    ``completed_past_deadline`` (docs/serving.md).  Absent (None) means
    no deadline — the pre-deadline trace schema is unchanged.
    ``prompt_period`` tiles the request's prompt embeddings from a
    seeded motif of that many positions (``data/synthetic.py``) — the
    repeating-structure variant that gives the n-gram drafter real
    lookup structure; None (the default) keeps the original fully
    random prompts and the original serialisation.
    ``prefix_len``/``prefix_seed`` mark the request a member of a
    shared-prefix group (``generate_trace(prefix_groups=…)``): its
    first ``prefix_len`` prompt positions are drawn from the GROUP seed
    ``prefix_seed``, bit-identical across the group, which is what the
    engine's prefix trie content-addresses.  Absent (None) keeps the
    original prompts and serialisation."""

    rid: int
    arrival_s: float
    prompt_len: int
    output_len: int
    seed: int
    deadline_s: Optional[float] = None
    prompt_period: Optional[int] = None
    prefix_len: Optional[int] = None
    prefix_seed: Optional[int] = None

    @property
    def total_tokens(self) -> int:
        return self.prompt_len + self.output_len


@dataclass(frozen=True)
class TrafficTrace:
    """A replayable request trace (sorted by arrival time)."""

    kind: str
    seed: int
    params: dict[str, Any]
    requests: tuple[Request, ...] = field(default_factory=tuple)

    def __iter__(self) -> Iterator[Request]:
        return iter(self.requests)

    def __len__(self) -> int:
        return len(self.requests)

    @property
    def horizon_s(self) -> float:
        """Arrival time of the last request (0 for an empty trace)."""
        return self.requests[-1].arrival_s if self.requests else 0.0

    @property
    def max_total_tokens(self) -> int:
        return max((r.total_tokens for r in self.requests), default=0)

    @property
    def max_prompt_len(self) -> int:
        return max((r.prompt_len for r in self.requests), default=0)

    def to_dict(self) -> dict[str, Any]:
        return {
            "schema": TRACE_SCHEMA,
            "kind": self.kind,
            "seed": self.seed,
            "params": dict(self.params),
            # optional fields serialise only when set, so committed
            # pre-feature traces stay byte-stable
            "requests": [
                {k: v for k, v in asdict(r).items()
                 if k not in ("deadline_s", "prompt_period",
                              "prefix_len", "prefix_seed")
                 or v is not None}
                for r in self.requests
            ],
        }

    def save(self, path: "str | Path") -> Path:
        from dlbb_tpu.utils.config import atomic_write_text

        return atomic_write_text(json.dumps(self.to_dict(), indent=2),
                                 Path(path))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TrafficTrace":
        if d.get("schema") != TRACE_SCHEMA:
            raise ValueError(
                f"not a serving trace (schema={d.get('schema')!r}, "
                f"expected {TRACE_SCHEMA!r})"
            )
        reqs = tuple(Request(**r) for r in d["requests"])
        return cls(kind=d["kind"], seed=int(d["seed"]),
                   params=dict(d.get("params", {})), requests=reqs)

    @classmethod
    def load(cls, path: "str | Path") -> "TrafficTrace":
        from dlbb_tpu.resilience import inject

        text = Path(path).read_text()
        if inject.fire("serve-trace-corrupt"):
            # chaos harness: model a torn/corrupt trace file on disk —
            # the load below must fail CLOSED with a chained error, and
            # the caller must publish nothing
            text = text[:int(len(text) * inject.param("torn_fraction"))]
        try:
            d = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"serving trace {path} is corrupt or truncated "
                "(refusing to serve a partial trace)"
            ) from e
        return cls.from_dict(d)


def _lognormal_lengths(rng: np.random.Generator, n: int,
                       lo: int, hi: int) -> np.ndarray:
    """Clipped-lognormal integer lengths in ``[lo, hi]`` — median near the
    geometric middle of the range, with the long right tail clipped."""
    if lo < 1 or lo > hi:
        raise ValueError(
            f"length bounds must satisfy 1 <= lo <= hi, got [{lo}, {hi}]"
        )
    if lo == hi:
        return np.full(n, lo, dtype=np.int64)
    mu = 0.5 * (math.log(lo) + math.log(hi))
    sigma = (math.log(hi) - math.log(lo)) / 4.0
    raw = rng.lognormal(mean=mu, sigma=sigma, size=n)
    return np.clip(np.round(raw).astype(np.int64), lo, hi)


def _poisson_arrivals(rng: np.random.Generator, n: int,
                      rate: float) -> np.ndarray:
    return np.cumsum(rng.exponential(1.0 / rate, size=n))


def _bursty_arrivals(rng: np.random.Generator, n: int, rate: float,
                     burst_factor: float, dwell_s: float) -> np.ndarray:
    """2-state MMPP: exponential dwells (mean ``dwell_s``) alternating
    between ``rate`` and ``rate * burst_factor``."""
    arrivals = np.empty(n)
    t = 0.0
    burst = False
    state_end = float(rng.exponential(dwell_s))
    for i in range(n):
        while True:
            r = rate * burst_factor if burst else rate
            gap = float(rng.exponential(1.0 / r))
            if t + gap <= state_end:
                t += gap
                arrivals[i] = t
                break
            # the gap straddles a state switch: advance to the boundary
            # and resample in the new state (memorylessness makes the
            # truncated draw exact)
            t = state_end
            burst = not burst
            state_end = t + float(rng.exponential(dwell_s))
    return arrivals


def _diurnal_arrivals(rng: np.random.Generator, n: int, rate: float,
                      period_s: float, depth: float) -> np.ndarray:
    """Lewis-Shedler thinning of a ``rate * (1 + depth*sin)`` profile."""
    if not 0.0 <= depth < 1.0:
        raise ValueError(f"diurnal depth must be in [0, 1), got {depth}")
    rate_max = rate * (1.0 + depth)
    arrivals = np.empty(n)
    t = 0.0
    i = 0
    while i < n:
        t += float(rng.exponential(1.0 / rate_max))
        lam = rate * (1.0 + depth * math.sin(2.0 * math.pi * t / period_s))
        if rng.uniform() * rate_max <= lam:
            arrivals[i] = t
            i += 1
    return arrivals


def generate_trace(
    kind: str,
    num_requests: int,
    seed: int = 42,
    rate: float = 32.0,
    prompt_range: tuple[int, int] = (8, 96),
    output_range: tuple[int, int] = (4, 48),
    burst_factor: float = 6.0,
    dwell_s: float = 0.5,
    period_s: float = 4.0,
    depth: float = 0.8,
    deadline_s: Optional[float] = None,
    prompt_period: Optional[int] = None,
    prefix_groups: Optional[int] = None,
    prefix_len: Optional[int] = None,
) -> TrafficTrace:
    """Generate a seeded, replayable trace.

    ``rate`` is the mean arrival rate in req/s (the calm-state rate for
    ``bursty``, the mean of the sinusoid for ``diurnal``); length bounds
    are inclusive.  ``deadline_s`` stamps every request with that SLO
    (seconds from arrival; None = no deadlines, the original schema).
    ``prompt_period`` stamps every request with a repeating-structure
    prompt (motif of that many positions tiled to the prompt length —
    the speculative-decoding bench's trace variant; None = fully random
    prompts, the original schema).  ``prefix_groups`` splits the trace
    into that many seeded shared-prefix populations: each request joins
    a group and shares its first ``prefix_len`` prompt positions
    (clamped to ``prompt_len - 1``; default the midpoint of
    ``prompt_range``) with every other member — the system-prompt /
    few-shot-header traffic shape the prefix cache exploits.  The group
    draws happen AFTER all original draws, so prefix-free traces stay
    byte-identical to the pre-feature schema.  The same ``(kind,
    num_requests, seed, params)`` always yields the identical trace.
    """
    if kind not in TRACE_KINDS:
        raise ValueError(
            f"unknown trace kind {kind!r} (expected one of {TRACE_KINDS})"
        )
    if num_requests <= 0:
        raise ValueError(f"num_requests must be > 0, got {num_requests}")
    if rate <= 0:
        raise ValueError(f"rate must be > 0 req/s, got {rate}")
    rng = np.random.default_rng(seed)
    if kind == "poisson":
        arrivals = _poisson_arrivals(rng, num_requests, rate)
        params: dict[str, Any] = {"rate": rate}
    elif kind == "bursty":
        arrivals = _bursty_arrivals(rng, num_requests, rate,
                                    burst_factor, dwell_s)
        params = {"rate": rate, "burst_factor": burst_factor,
                  "dwell_s": dwell_s}
    else:
        arrivals = _diurnal_arrivals(rng, num_requests, rate,
                                     period_s, depth)
        params = {"rate": rate, "period_s": period_s, "depth": depth}
    prompts = _lognormal_lengths(rng, num_requests, *prompt_range)
    outputs = _lognormal_lengths(rng, num_requests, *output_range)
    seeds = rng.integers(0, 2**31 - 1, size=num_requests)
    params.update({"prompt_range": list(prompt_range),
                   "output_range": list(output_range)})
    if deadline_s is not None:
        if deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 seconds, got {deadline_s}"
            )
        params["deadline_s"] = deadline_s
    if prompt_period is not None:
        if prompt_period < 1:
            raise ValueError(
                f"prompt_period must be >= 1, got {prompt_period}"
            )
        params["prompt_period"] = prompt_period
    prefix_lens = prefix_seeds = None
    if prefix_groups is not None:
        if prefix_groups < 1:
            raise ValueError(
                f"prefix_groups must be >= 1, got {prefix_groups}"
            )
        if prefix_len is None:
            prefix_len = (prompt_range[0] + prompt_range[1]) // 2
        if prefix_len < 1:
            raise ValueError(f"prefix_len must be >= 1, got {prefix_len}")
        if prompt_range[0] < 2:
            raise ValueError(
                "prefix_groups needs prompt_range lo >= 2 (every request "
                "must keep at least one per-request position after its "
                "shared prefix)"
            )
        # drawn after every original draw: prefix-free traces are
        # byte-identical to the pre-feature schema
        group_seeds = rng.integers(0, 2**31 - 1, size=prefix_groups)
        membership = rng.integers(0, prefix_groups, size=num_requests)
        prefix_seeds = [int(group_seeds[g]) for g in membership]
        prefix_lens = [min(prefix_len, int(prompts[i]) - 1)
                       for i in range(num_requests)]
        params.update({"prefix_groups": prefix_groups,
                       "prefix_len": prefix_len})
    elif prefix_len is not None:
        raise ValueError("prefix_len requires prefix_groups")
    requests = tuple(
        Request(rid=i, arrival_s=float(arrivals[i]),
                prompt_len=int(prompts[i]), output_len=int(outputs[i]),
                seed=int(seeds[i]), deadline_s=deadline_s,
                prompt_period=prompt_period,
                prefix_len=None if prefix_lens is None else prefix_lens[i],
                prefix_seed=(None if prefix_seeds is None
                             else prefix_seeds[i]))
        for i in range(num_requests)
    )
    return TrafficTrace(kind=kind, seed=seed, params=params,
                        requests=requests)
