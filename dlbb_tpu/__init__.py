"""dlbb_tpu — a TPU-native (JAX/XLA) distributed-communication benchmark framework.

Brand-new implementation of the capabilities of
``hardik-jinda/distributed-llm-backend-benchmark`` (reference mounted read-only at
``/root/reference``), re-designed TPU-first:

- ``comm``   — device-mesh bootstrap + collective op registry (shard_map over
  ``jax.lax`` collectives), replacing the reference's MPI/Gloo/oneCCL process
  groups (reference ``run_mpi.py:29-49``, ``collectives/1d/dsgloo.py:53-67``).
- ``bench``  — one declarative sweep/timing harness replacing the reference's
  eight near-identical benchmark scripts (``collectives/{1d,3d}/*.py``).
- ``stats``  — offline statistics pipeline with reference-compatible JSON/CSV
  schemas (``collectives/1d/stats.py``, ``collectives/3d/stats.py``).
- ``models`` — Megatron-style tensor-parallel decoder via GSPMD partition specs
  (reference ``models.py``), 1B/7B/13B configs.
- ``train``  — DDP / ZeRO-1 training loop (reference ``test/ccl.py:59-117``).
- ``data``   — synthetic seeded embedding batches (reference ``data_gen.py``).
- ``utils``  — metrics, timing, config IO, system info (reference ``utils.py``).

No code is copied from the reference; citations in docstrings are for parity
auditing only.
"""

__version__ = "0.1.0"
