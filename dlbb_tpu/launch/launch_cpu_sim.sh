#!/usr/bin/env bash
# Development launcher on the CPU-simulated mesh — the analogue of the
# reference's localhost rank sweeps (collectives/launch_openmpi.sh:5-12:
# `for np in 2 4 8 16; do mpirun -np $np ...`).  One process, N fake devices.
#
# Usage:
#   ./launch_cpu_sim.sh 8 bench1d --ranks 2 4 8
#   ./launch_cpu_sim.sh 8 e2e --config dlbb_tpu/configs/baseline_config.yaml

set -euo pipefail

NDEV="${1:?usage: launch_cpu_sim.sh <num_devices> <subcommand> [args...]}"
shift

exec python -m dlbb_tpu.cli "$@" --simulate "$NDEV"
