#!/usr/bin/env bash
# TPU-pod launcher — the replacement for the reference's mpirun/deepspeed
# launch layer (launch_openmpi.sh:19-26, collectives/3d/launch_dsccl.sh:69-74).
#
# On a TPU pod slice every host runs the same command; jax.distributed
# auto-discovers the coordinator from the TPU metadata server (no -np / rank
# tables needed — the analogue of mpirun's process spawning is the pod
# runtime itself).
#
# Usage (run on every pod host, e.g. via `gcloud compute tpus tpu-vm ssh
# --worker=all --command=...`):
#   ./launch_tpu_pod.sh bench1d --ranks 8 16 --variant ring
#   ./launch_tpu_pod.sh bench3d --ranks 16
#   ./launch_tpu_pod.sh e2e --config dlbb_tpu/configs/baseline_config.yaml
#
# Tuning variants that carry XLA flags (see dlbb_tpu/comm/variants.py) must
# have them set at process start; pass VARIANT_XLA_FLAGS:
#   VARIANT_XLA_FLAGS="--xla_tpu_all_reduce_combine_threshold_bytes=4194304" \
#     ./launch_tpu_pod.sh bench1d --variant combine4mb ...

set -euo pipefail

export XLA_FLAGS="${XLA_FLAGS:-} ${VARIANT_XLA_FLAGS:-}"
export DLBB_DISTRIBUTED=auto   # dlbb_tpu.cli calls initialize_distributed(auto=True)

exec python -m dlbb_tpu.cli "$@"
