#!/usr/bin/env bash
# TPU-pod launcher — the replacement for the reference's mpirun/deepspeed
# launch layer (launch_openmpi.sh:19-26, collectives/3d/launch_dsccl.sh:69-74).
#
# On a TPU pod slice every host runs the same command; jax.distributed
# auto-discovers the coordinator from the TPU metadata server (no -np / rank
# tables needed — the analogue of mpirun's process spawning is the pod
# runtime itself).
#
# Usage (run on every pod host, e.g. via `gcloud compute tpus tpu-vm ssh
# --worker=all --command=...`):
#   ./launch_tpu_pod.sh bench1d --ranks 8 16 --variant ring
#   ./launch_tpu_pod.sh bench3d --ranks 16
#   ./launch_tpu_pod.sh e2e --config dlbb_tpu/configs/baseline_config.yaml
#
# Tuning variants that carry XLA flags (dlbb_tpu/comm/variants.py, e.g.
# combine4mb / combine128mb — the CCL_FUSION_BYTES_THRESHOLD analogue) need
# them in XLA_FLAGS before process start.  The launcher resolves them from
# the --variant name automatically; VARIANT_XLA_FLAGS remains available as a
# manual override for ad-hoc flag experiments:
#   VARIANT_XLA_FLAGS="--xla_tpu_all_reduce_combine_threshold_bytes=1048576" \
#     ./launch_tpu_pod.sh bench1d ...
#
# DLBB_LAUNCH_DRYRUN=1 prints the resolved environment + command instead of
# exec'ing — used by tests/test_launch.py to pin the flag-injection contract
# without a pod.

set -euo pipefail

# Resolve --variant <name> (both "--variant name" and "--variant=name",
# matching what dlbb_tpu.cli's argparse accepts) from the arguments.
VARIANT=""
prev=""
for arg in "$@"; do
  if [ "$prev" = "--variant" ]; then
    VARIANT="$arg"
  fi
  case "$arg" in
    --variant=*) VARIANT="${arg#--variant=}" ;;
  esac
  prev="$arg"
done

RESOLVED_FLAGS=""
if [ -n "$VARIANT" ]; then
  # Ask the variant registry for process-start XLA flags.  JAX_PLATFORMS=cpu
  # keeps the helper import from touching the TPU runtime before the real
  # process starts.
  RESOLVED_FLAGS=$(JAX_PLATFORMS=cpu python - "$VARIANT" <<'PYEOF'
import sys
from dlbb_tpu.comm.variants import get_variant

print(" ".join(get_variant(sys.argv[1]).xla_flags))
PYEOF
)
fi

export XLA_FLAGS="${XLA_FLAGS:-} ${RESOLVED_FLAGS} ${VARIANT_XLA_FLAGS:-}"
export DLBB_DISTRIBUTED=auto   # dlbb_tpu.cli calls initialize_distributed(auto=True)

if [ "${DLBB_LAUNCH_DRYRUN:-0}" = "1" ]; then
  echo "XLA_FLAGS=${XLA_FLAGS}"
  echo "DLBB_DISTRIBUTED=${DLBB_DISTRIBUTED}"
  echo "exec python -m dlbb_tpu.cli $*"
  exit 0
fi

exec python -m dlbb_tpu.cli "$@"
