"""Named tuning variants — the TPU analogue of the oneCCL tuning matrix.

The reference steers collective algorithm/topology/fusion through env vars and
re-edited module constants (``collectives/3d/launch_dsccl.sh:34-65``:
``CCL_ALLREDUCE`` in {topo,direct,rabenseifner,nreduce,ring,double_tree,
recursive_doubling,2d}, ``CCL_WORKER_COUNT``, ``CCL_FUSION*``,
``CCL_ATL_TRANSPORT``), producing 19 result directories (SURVEY §2.3).

On TPU the corresponding knobs are:

- **mesh topology / axis order** — a 1D ring rides the ICI ring; a multi-axis
  mesh makes XLA reduce hierarchically per axis (the "2d"/"topo" analogue);
- **explicit hierarchical reduction** — ``allreduce_hierarchical`` psums one
  axis at a time (ring-of-rings);
- **XLA collective combiner thresholds** — the fusion analogue of
  ``CCL_FUSION_BYTES_THRESHOLD``; these are process-level ``XLA_FLAGS``
  (e.g. ``--xla_tpu_all_reduce_combine_threshold_bytes``) and must be set
  before backend init, so variants carry them as metadata for launchers.

Variants are first-class named configs (SURVEY §2.3 requirement: "named-variant
config rather than edit-the-file"); the variant name lands in the result
JSON's ``implementation`` field so stats curves stay comparable across runs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from dlbb_tpu.comm.mesh import MeshSpec


@dataclass(frozen=True)
class Variant:
    """One named point in the tuning space."""

    name: str
    description: str = ""
    # mesh shape override; None = flat ring of the sweep's rank count
    mesh_shape: Optional[tuple[int, ...]] = None
    mesh_axis_names: Optional[tuple[str, ...]] = None
    # use the explicit per-axis hierarchical allreduce builder
    hierarchical: bool = False
    # collective-matmul schedule for the ag_matmul / matmul_rs micro-ops:
    # None = fused (all-gather / psum_scatter, the GSPMD lowering);
    # "ring" / "bidir" = the ring-decomposed overlapped schedule of
    # dlbb_tpu/parallel/collective_matmul.py.  Ignored by every other op
    # (a tuning knob, like `hierarchical` for allreduce).
    overlap_schedule: Optional[str] = None
    # wire compression for the allreduce_q / reducescatter_q micro-ops:
    # None = the op's default (int8); "int8" / "fp8" select the wire
    # dtype explicitly (dlbb_tpu/comm/compression.py, docs/compression.md).
    # Ignored by every other op, same convention as `overlap_schedule`.
    compression: Optional[str] = None
    # accumulation dtype for the compressed ring ("float32" default;
    # "bfloat16" is the memory/speed-reduced variant the sweep prices)
    accum_dtype: Optional[str] = None
    # XLA_FLAGS fragments a launcher must set before process start
    xla_flags: tuple[str, ...] = ()
    # per-computation XLA compiler options (jit(...).lower().compile(...)),
    # applied by the sweep/e2e/train harnesses — unlike xla_flags these need
    # no relaunch and work on any PJRT backend that knows the option
    compiler_options: tuple[tuple[str, str], ...] = ()
    # extra metadata recorded into result JSON, as (key, value) pairs so the
    # frozen dataclass stays hashable
    extra: tuple[tuple[str, str], ...] = ()

    def mesh_spec(self, num_ranks: int) -> MeshSpec:
        if self.mesh_shape is not None:
            import math

            if math.prod(self.mesh_shape) != num_ranks:
                raise ValueError(
                    f"variant {self.name!r} mesh {self.mesh_shape} does not "
                    f"cover {num_ranks} ranks"
                )
            names = self.mesh_axis_names or tuple(
                f"ax{i}" for i in range(len(self.mesh_shape))
            )
            return MeshSpec(self.mesh_shape, names)
        return MeshSpec.ring(num_ranks)


VARIANTS: dict[str, Variant] = {
    "default": Variant(
        "default",
        "flat 1D ring mesh, XLA-chosen reduction (analogue of CCL topo default)",
    ),
    "ring": Variant(
        "ring",
        "flat 1D ring mesh — explicit analogue of CCL_ALLREDUCE=ring",
    ),
    "grid2x4": Variant(
        "grid2x4",
        "2x4 mesh (outer-major axis order), joint reduction over both axes "
        "(1D-ring vs 2D-mesh shape axis)",
        mesh_shape=(2, 4),
        mesh_axis_names=("outer", "inner"),
    ),
    "grid4x2": Variant(
        "grid4x2",
        "4x2 mesh — axis-order transpose of grid2x4; device order differs, "
        "so the collective schedule XLA derives differs",
        mesh_shape=(4, 2),
        mesh_axis_names=("outer", "inner"),
    ),
    "hier2x4": Variant(
        "hier2x4",
        "2x4 mesh, explicit per-axis hierarchical psum: outer(2) then "
        "inner(4)",
        mesh_shape=(2, 4),
        mesh_axis_names=("outer", "inner"),
        hierarchical=True,
    ),
    "hier4x2": Variant(
        "hier4x2",
        "4x2 mesh, explicit per-axis hierarchical psum: outer(4) then "
        "inner(2) — reduction-order transpose of hier2x4",
        mesh_shape=(4, 2),
        mesh_axis_names=("outer", "inner"),
        hierarchical=True,
    ),
    "grid2x8": Variant(
        "grid2x8",
        "2x8 mesh (16 ranks), joint reduction over both axes — the 16-rank "
        "rung of the mesh-shape tuning axis",
        mesh_shape=(2, 8),
        mesh_axis_names=("outer", "inner"),
    ),
    "grid4x4": Variant(
        "grid4x4",
        "4x4 mesh (16 ranks), joint reduction — square alternative to 2x8",
        mesh_shape=(4, 4),
        mesh_axis_names=("outer", "inner"),
    ),
    "hier2x8": Variant(
        "hier2x8",
        "2x8 mesh, explicit per-axis hierarchical psum: outer(2) then "
        "inner(8)",
        mesh_shape=(2, 8),
        mesh_axis_names=("outer", "inner"),
        hierarchical=True,
    ),
    "hier4x4": Variant(
        "hier4x4",
        "4x4 mesh, explicit per-axis hierarchical psum over equal halves",
        mesh_shape=(4, 4),
        mesh_axis_names=("outer", "inner"),
        hierarchical=True,
    ),
    "grid2x2x2": Variant(
        "grid2x2x2",
        "2x2x2 mesh, joint reduction over all axes (CCL_ALLREDUCE=2d analogue; "
        "BASELINE.json config 3)",
        mesh_shape=(2, 2, 2),
        mesh_axis_names=("x", "y", "z"),
    ),
    "hier2x2x2": Variant(
        "hier2x2x2",
        "2x2x2 mesh, explicit per-axis hierarchical psum (ICI ring-of-rings, "
        "double_tree/rabenseifner analogue)",
        mesh_shape=(2, 2, 2),
        mesh_axis_names=("x", "y", "z"),
        hierarchical=True,
    ),
    "overlap_ring": Variant(
        "overlap_ring",
        "ring-decomposed collective matmul: ppermute chain hides the "
        "gather/scatter behind per-shard partial matmuls (ag_matmul / "
        "matmul_rs micro-ops; fused baseline = the default variant)",
        overlap_schedule="ring",
    ),
    "overlap_bidir": Variant(
        "overlap_bidir",
        "bidirectional-ring collective matmul: both ICI directions per "
        "step — half the hops for ag_matmul, half-sized messages both "
        "ways for matmul_rs",
        overlap_schedule="bidir",
    ),
    "compress_int8": Variant(
        "compress_int8",
        "quantised-wire collectives: int8 chunked-symmetric wire, fp32 "
        "accumulation (allreduce_q / reducescatter_q micro-ops; bf16 "
        "fused baseline = the default variant on allreduce/reducescatter)",
        compression="int8",
    ),
    "compress_fp8": Variant(
        "compress_fp8",
        "quantised-wire collectives: fp8(e4m3) wire, fp32 accumulation — "
        "same byte footprint as int8, different rounding behaviour",
        compression="fp8",
    ),
    "compress_int8_bf16acc": Variant(
        "compress_int8_bf16acc",
        "int8 wire with bf16 ring accumulation — the reduced-precision "
        "accumulate leg of the bandwidth-vs-accuracy axis",
        compression="int8",
        accum_dtype="bfloat16",
    ),
    "nofuse": Variant(
        "nofuse",
        "collective-combiner HLO passes disabled (CCL_FUSION_ENABLE=0 "
        "analogue) — per-computation compiler option, no relaunch needed; "
        "measurable on many-collective programs (DDP/ZeRO train steps)",
        compiler_options=(
            ("xla_disable_hlo_passes",
             "all-reduce-combiner,all-gather-combiner,reduce-scatter-combiner"),
        ),
    ),
    # Threshold tuning (CCL_FUSION_BYTES_THRESHOLD analogue) exists only as
    # process-start XLA_FLAGS on real TPU pods; this image's PJRT plugin
    # exposes no combiner-threshold compile option (verified: both XLA_FLAGS
    # parsing and compiler_options reject it), so these stay launcher
    # metadata for pod runs (launch/launch_tpu_pod.sh).
    "combine4mb": Variant(
        "combine4mb",
        "all-reduce combiner threshold 4 MiB (CCL_FUSION_BYTES_THRESHOLD "
        "analogue; pod-launcher XLA_FLAGS, not executable on this image)",
        xla_flags=("--xla_tpu_all_reduce_combine_threshold_bytes=4194304",),
    ),
    "combine128mb": Variant(
        "combine128mb",
        "all-reduce combiner threshold 128 MiB (pod-launcher XLA_FLAGS, not "
        "executable on this image)",
        xla_flags=("--xla_tpu_all_reduce_combine_threshold_bytes=134217728",),
    ),
}


def get_variant(name: str) -> Variant:
    try:
        return VARIANTS[name]
    except KeyError:
        raise KeyError(f"unknown variant {name!r}; known: {sorted(VARIANTS)}") from None
