"""Device-mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's bootstrap / process-group layer:

- reference ``run_mpi.py:29-49`` (``initialize_mpi_backend`` /
  ``cleanup_mpi_backend`` via mpi4py ``MPI.COMM_WORLD``),
- reference ``collectives/1d/dsgloo.py:53-67`` and ``dsccl.py:47-57``
  (``deepspeed.init_distributed``),
- reference rank/core binding tables ``collectives/3d/config_{4,8}.txt``.

Instead of mpirun-spawned ranks holding an opaque communicator, we build a
``jax.sharding.Mesh`` over the devices XLA exposes.  "Rank count" becomes the
mesh size; "topology tuning" becomes the mesh *shape* (1D ring vs multi-axis),
which is how ICI reductions are steered on TPU.

Development happens on a CPU-simulated mesh:
``XLA_FLAGS=--xla_force_host_platform_device_count=N JAX_PLATFORMS=cpu``
gives N fake devices in one process — the idiomatic JAX analogue of
``mpirun -np N`` on localhost (reference ``collectives/launch_openmpi.sh:5-12``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Single flat collective axis used by the 1D microbenchmarks — the analogue of
# MPI_COMM_WORLD's rank dimension.
DEFAULT_AXIS = "ranks"


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh description.

    Replaces the reference's ``RANK_COUNTS`` module constants
    (``collectives/1d/openmpi.py:19-20``) and core-binding tables with a
    first-class config object.

    shape:      devices per mesh axis, e.g. ``(8,)`` or ``(2, 2, 2)``.
    axis_names: one name per axis, e.g. ``("ranks",)`` or ``("x","y","z")``.
    """

    shape: tuple[int, ...]
    axis_names: tuple[str, ...] = (DEFAULT_AXIS,)

    def __post_init__(self) -> None:
        if len(self.shape) != len(self.axis_names):
            raise ValueError(
                f"shape {self.shape} and axis_names {self.axis_names} "
                "must have the same length"
            )

    @classmethod
    def ring(cls, num_ranks: int, axis: str = DEFAULT_AXIS) -> "MeshSpec":
        """1D ring of ``num_ranks`` devices — the default microbenchmark mesh."""
        return cls((num_ranks,), (axis,))

    @classmethod
    def grid(cls, shape: Sequence[int], axis_names: Sequence[str]) -> "MeshSpec":
        """Multi-axis mesh, e.g. ``grid((2,2,2), ("x","y","z"))`` for the
        hierarchical-allreduce benchmark (BASELINE.json config 3)."""
        return cls(tuple(shape), tuple(axis_names))

    @property
    def num_ranks(self) -> int:
        return math.prod(self.shape)

    @property
    def name(self) -> str:
        return "x".join(str(s) for s in self.shape)


def available_devices(platform: Optional[str] = None) -> list:
    """All addressable-or-not devices, optionally filtered by platform."""
    if platform is None:
        return list(jax.devices())
    return list(jax.devices(platform))


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` for ``spec`` from the first
    ``spec.num_ranks`` devices.

    Mirrors the reference's world-size gate (``collectives/1d/openmpi.py:210-214``,
    ``run_mpi.py:73-77``): raises if fewer devices are available than the spec
    needs, so sweeps can skip infeasible rank counts.
    """
    devs = list(devices) if devices is not None else available_devices()
    n = spec.num_ranks
    if len(devs) < n:
        raise ValueError(
            f"mesh spec {spec.shape} needs {n} devices, "
            f"only {len(devs)} available"
        )
    grid = np.asarray(devs[:n], dtype=object).reshape(spec.shape)
    return Mesh(grid, spec.axis_names)


# (spec, device identity) -> Mesh.  jax.sharding.Mesh equality is cheap but
# object identity matters downstream: jitted programs, NamedShardings, and
# the sweep scheduler's work-unit/payload cache keys all want one Mesh per
# topology per process, not a fresh object per run_sweep call.
_MESH_CACHE: dict[tuple, Mesh] = {}


def get_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """``build_mesh`` with per-process memoisation.

    Repeated sweeps over the same topology (the publisher's stage loops, a
    resume re-run, the 1D/3D grids sharing a rank count) reuse one
    ``Mesh`` object instead of rebuilding it per ``run_sweep`` call.  Keyed
    by the spec and the identity of the devices that would populate it, so
    an explicit ``devices`` subset never aliases the default-device mesh.
    """
    devs = list(devices) if devices is not None else available_devices()
    key = (
        spec.shape,
        spec.axis_names,
        tuple(id(d) for d in devs[: spec.num_ranks]),
    )
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = build_mesh(spec, devices=devs)
        _MESH_CACHE[key] = mesh
    return mesh


def build_parallelism_mesh(
    data_parallel: int = 1,
    sequence_parallel: int = 1,
    pipeline_parallel: int = 1,
    tensor_parallel: int = 1,
    expert_parallel: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """The model-parallelism mesh shared by the E2E and train harnesses:
    ``(dp[, sp][, pp][, ep], tp)``.  dp is always present (outermost),
    sp/pp/ep only when > 1, and tp always innermost — the per-layer TP
    allreduces are the most frequent collective, so tp gets the fastest
    ICI neighbours."""
    shape, names = [data_parallel], ["dp"]
    if sequence_parallel > 1:
        shape.append(sequence_parallel)
        names.append("sp")
    if pipeline_parallel > 1:
        shape.append(pipeline_parallel)
        names.append("pp")
    if expert_parallel > 1:
        shape.append(expert_parallel)
        names.append("ep")
    shape.append(tensor_parallel)
    names.append("tp")
    return build_mesh(MeshSpec.grid(tuple(shape), tuple(names)),
                      devices=devices)


def partition_devices(
    devices: Optional[Sequence] = None,
    groups: int = 1,
) -> list[list]:
    """Partition the device list into ``groups`` contiguous, equal-size,
    disjoint failure domains — the replica sub-meshes of the serving
    fleet (``serve/fleet.py``).

    Contiguity matters: XLA enumerates the simulated (and, on hardware,
    the physically-adjacent) devices in order, so contiguous slices give
    each replica the tightest ICI neighbourhood and guarantee no device
    is shared between domains — one replica's failure can never corrupt
    another's collectives.  Raises when the device count does not divide
    evenly (a lopsided fleet would skew every per-replica capacity
    claim)."""
    devs = list(devices) if devices is not None else available_devices()
    if groups < 1:
        raise ValueError(f"need at least one device group, got {groups}")
    if len(devs) % groups != 0:
        raise ValueError(
            f"{len(devs)} device(s) do not partition into {groups} "
            "equal failure domains"
        )
    per = len(devs) // groups
    return [devs[i * per:(i + 1) * per] for i in range(groups)]


def fault_domain_record(groups: Sequence[Sequence]) -> dict[str, list[int]]:
    """JSON-able ``fault_domains`` map (replica id -> device ids) for
    the topology record / serving manifest — the key fleet artifacts
    carry so fleet runs never silently aggregate with single-replica
    runs (``utils/simulate.topology_record``)."""
    return {
        str(i): [int(getattr(d, "id", j)) for j, d in enumerate(grp)]
        for i, grp in enumerate(groups)
    }


def mesh_num_ranks(mesh: Mesh, axes: Optional[Sequence[str]] = None) -> int:
    """Total ranks along ``axes`` (all axes if None)."""
    names = tuple(axes) if axes is not None else tuple(mesh.axis_names)
    return math.prod(mesh.shape[a] for a in names)


def flat_axes(mesh: Mesh) -> tuple[str, ...]:
    """All axis names of a mesh, for collectives that reduce over the whole
    mesh (hierarchical variants reduce over them one at a time instead)."""
    return tuple(mesh.axis_names)


@dataclass
class DistributedContext:
    """What the reference's ``initialize_mpi_backend`` returns — ``(rank,
    world_size, comm)`` (``run_mpi.py:29-43``) — recast for JAX multi-host:
    process index/count at the host level, device count at the chip level."""

    process_id: int = 0
    num_processes: int = 1
    num_devices: int = field(default_factory=lambda: len(jax.devices()))

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> DistributedContext:
    """Multi-host bootstrap — the TPU-pod analogue of ``mpirun`` +
    ``MPI.COMM_WORLD`` (reference ``run_mpi.py:29-43``) and of the DeepSpeed
    launcher env handshake (``collectives/3d/launch_dsccl.sh:69-74``).

    Three modes:
    - explicit args → ``jax.distributed.initialize`` with them;
    - ``auto=True`` (what pod launchers pass — ``launch/launch_tpu_pod.sh``) →
      argument-free ``jax.distributed.initialize()``, which auto-discovers
      coordinator/processes from the TPU metadata server;
    - no args, ``auto=False`` (the default) → single-host no-op, so library
      users on one host or the CPU-simulated mesh never touch the
      coordinator handshake.
    """
    if num_processes is not None or coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif auto:
        # fail fast: auto=True means "we are on a pod" (launch_tpu_pod.sh);
        # degrading one host to single-process while its peers initialize
        # would hang the collective or silently mislabel single-host numbers
        jax.distributed.initialize()
    return DistributedContext(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        num_devices=len(jax.devices()),
    )
