"""Mesh + collectives layer (L0/L1 replacement).

The reference bottoms out in external C/C++ comm libraries driven through
mpi4py / deepspeed.comm (reference SURVEY L0-L1). The TPU-native equivalent is
XLA's collective runtime over ICI/DCN, reached through ``jax.lax`` collectives
inside ``jax.shard_map`` over a ``jax.sharding.Mesh``.
"""

from dlbb_tpu.comm.mesh import (
    DEFAULT_AXIS,
    MeshSpec,
    build_mesh,
    build_parallelism_mesh,
    flat_axes,
    initialize_distributed,
    mesh_num_ranks,
)
from dlbb_tpu.comm.ops import (
    OPERATIONS,
    CollectiveOp,
    get_op,
    make_payload,
)
from dlbb_tpu.comm.variants import VARIANTS, Variant, get_variant

__all__ = [
    "DEFAULT_AXIS",
    "MeshSpec",
    "build_mesh",
    "build_parallelism_mesh",
    "flat_axes",
    "initialize_distributed",
    "mesh_num_ranks",
    "OPERATIONS",
    "CollectiveOp",
    "get_op",
    "make_payload",
    "VARIANTS",
    "Variant",
    "get_variant",
]
