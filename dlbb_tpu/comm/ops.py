"""Collective operation registry.

TPU-native re-design of the reference's per-backend benchmark functions
(``collectives/1d/openmpi.py:55-198``, ``collectives/1d/dsgloo.py:73-212``):
one registry of SPMD collectives built from ``jax.lax`` primitives under
``jax.shard_map``, instead of four copies of eight hand-written
MPI/torch.distributed wrappers.

Data model
----------
MPI programs are MIMD: every rank holds its *own* buffer.  The SPMD encoding
used here is a *global* array whose leading axis is the rank axis, sharded over
the mesh — device ``i`` holds row ``i``, exactly the per-rank buffer of the
reference.  Ops that send a buffer-per-peer (scatter/alltoall) take a global
``[P, P, n]`` array (device ``i`` holds its ``[P, n]`` sendbuf).

Root-rooted ops (broadcast / gather / scatter / reduce) have no native SPMD
analogue (SURVEY §7 "hard parts"); they are composed from symmetric
collectives + masking by ``lax.axis_index``:

- broadcast  = psum(where(rank == root, x, 0))            (exact: one term)
- reduce     = where(rank == root, psum(x), 0)
- gather     = where(rank == root, all_gather(x), 0)
- scatter    = psum-broadcast root's sendbuf, then slice own row

The ring sendrecv of the reference (``collectives/1d/openmpi.py:173-198``,
Isend/Irecv to (rank±1) mod P) maps to ``lax.ppermute`` with a ring
permutation, which XLA lowers to neighbour ICI transfers.

Reduce-scatter (``lax.psum_scatter``) is added beyond the reference's eight
ops because it is the primitive under ZeRO-1 (BASELINE.json config 5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlbb_tpu.comm.mesh import mesh_num_ranks
from dlbb_tpu.compat import axis_size, shard_map


@dataclass(frozen=True)
class CollectiveOp:
    """One benchmarkable collective.

    input_kind / output_kind:
      "per_rank"  — global ``[P, n]``, device i owns row i (one buffer/rank)
      "per_peer"  — global ``[P, P, n]``, device i owns slab i (one buffer per
                    peer, as for MPI_Scatter's root sendbuf / MPI_Alltoall)

    ``output_kind`` declares the op's *result* footprint the same way — e.g.
    allgather turns a per-rank input into a per-peer ``[P, P, n]`` output —
    so memory estimates (``runner._estimate_global_bytes``) derive their
    multipliers from the registry instead of hard-coded op-name lists.

    ``transient_kind`` declares the largest *intermediate* the op
    materialises beyond input+output (None for ops that stream through
    collectives directly): ``ag_matmul``'s fused schedule holds the
    gathered ``[B, P*S, H]`` activation on every device (per_peer:
    P^2 x payload globally), ``matmul_rs`` a full per-device partial
    product (per_rank) — without this the memory-cap gate would admit
    configs whose true footprint is ~P/2x the in+out estimate.

    make_chain(P) returns glue mapping the op's output back to a valid next
    input, used by chained timing (``dlbb_tpu.utils.timing``) to iterate the
    op inside one jitted loop without letting XLA hoist it; None means the
    output already has the input's shape and feeds back directly.
    """

    name: str
    input_kind: str
    output_kind: str
    build: Callable[..., Callable]  # (mesh, axes, root) -> fn(global) -> global
    make_chain: Optional[Callable[[int], Callable]] = None
    transient_kind: Optional[str] = None


# Payload RNG seed shared by make_payload and payload_cache_key: the cache
# key's contract (equal keys => numerically identical arrays) requires the
# two defaults to be THE SAME object, never two literals to keep in sync.
DEFAULT_PAYLOAD_SEED = 42


def _rank_id(axes: Sequence[str]) -> jax.Array:
    """Linearised rank index over possibly-multiple mesh axes."""
    idx = jnp.int32(0)
    for a in axes:
        idx = idx * axis_size(a) + jax.lax.axis_index(a)
    return idx


def _specs(mesh: Mesh, axes: Sequence[str], ndim: int) -> P:
    """PartitionSpec sharding the leading (rank) axis over ``axes``."""
    return P(tuple(axes), *([None] * (ndim - 1)))


def _wrap(mesh: Mesh, axes: Sequence[str], body, in_ndim: int, out_ndim: int):
    spec_in = _specs(mesh, axes, in_ndim)
    spec_out = _specs(mesh, axes, out_ndim)
    fn = shard_map(body, mesh=mesh, in_specs=spec_in, out_specs=spec_out)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# op builders — each returns fn(global_array) -> global_array
# ---------------------------------------------------------------------------


def _reduce_over(x, axes: Sequence[str], reduce_op: str):
    if reduce_op == "sum":
        return jax.lax.psum(x, tuple(axes))
    if reduce_op == "max":
        return jax.lax.pmax(x, tuple(axes))
    if reduce_op == "min":
        return jax.lax.pmin(x, tuple(axes))
    if reduce_op == "prod":
        # No pprod primitive: gather then reduce locally (exact, unlike
        # exp(psum(log)) which fails on zeros/negatives).
        g = jax.lax.all_gather(x, tuple(axes))
        return jnp.prod(g, axis=0)
    raise ValueError(f"unknown reduce op {reduce_op!r}")


def build_allreduce(mesh, axes, root=0, reduce_op="sum"):
    """MPI_Allreduce (reference ``collectives/1d/openmpi.py:55-67``;
    MAX/MIN/PROD variants per ``test/test_open.py:248``)."""

    def body(x):  # local [1, n]
        return _reduce_over(x, axes, reduce_op)

    return _wrap(mesh, axes, body, 2, 2)


def build_allreduce_hierarchical(mesh, axes, root=0, reduce_op="sum"):
    """Hierarchical allreduce: reduce one mesh axis at a time (e.g. 2x2x2),
    the ICI analogue of oneCCL's topo-aware algorithms
    (``collectives/3d/launch_dsccl.sh:46-47``; BASELINE.json config 3)."""
    if reduce_op != "sum":
        raise ValueError("hierarchical allreduce supports sum only")

    def body(x):
        for a in axes:
            x = jax.lax.psum(x, a)
        return x

    return _wrap(mesh, axes, body, 2, 2)


def build_allgather(mesh, axes, root=0):
    """MPI_Allgather (reference ``collectives/1d/openmpi.py:84-96``):
    per-rank [n] -> every rank holds [P*n]."""

    def body(x):  # local [1, *shape] -> [1, P, *shape]
        g = jax.lax.all_gather(x[0], tuple(axes))  # [P, *shape]
        return g[None]

    # Output keeps the per-rank payload structure — global [P, P, *shape],
    # consistent with gather — whether the payload is flat [n] (1D sweeps)
    # or (B, S, H) (3D sweeps).  PartitionSpecs shorter than the array rank
    # are padded with None, so the spec arity below covers both.
    return _wrap(mesh, axes, body, 2, 3)


def build_broadcast(mesh, axes, root=0):
    """MPI_Bcast from ``root`` (reference ``collectives/1d/openmpi.py:98-110``).
    Exact psum-of-masked: only the root contributes a non-zero term."""

    def body(x):
        contrib = jnp.where(_rank_id(axes) == root, x, jnp.zeros_like(x))
        return jax.lax.psum(contrib, tuple(axes))

    return _wrap(mesh, axes, body, 2, 2)


def build_gather(mesh, axes, root=0):
    """MPI_Gather to ``root`` (reference ``collectives/1d/openmpi.py:112-124``).
    Output [P, P, n]: root's slab holds every rank's buffer, others zero —
    SPMD has no "None on non-root", so non-root slabs are zeroed."""

    def body(x):  # local [1, n] -> [1, P, n]
        g = jax.lax.all_gather(x[0], tuple(axes))  # [P, n]
        keep = (_rank_id(axes) == root).astype(g.dtype)
        return (g * keep)[None]

    return _wrap(mesh, axes, body, 2, 3)


def build_scatter(mesh, axes, root=0):
    """MPI_Scatter from ``root`` (reference ``collectives/1d/openmpi.py:126-140``):
    root's [P, n] sendbuf -> rank i receives row i.  Broadcast root's sendbuf
    (psum of masked) then each rank slices its own row."""

    def body(x):  # local [1, P, n] -> [1, n]
        me = _rank_id(axes)
        contrib = jnp.where(me == root, x[0], jnp.zeros_like(x[0]))
        sendbuf = jax.lax.psum(contrib, tuple(axes))  # [P, n] — root's buffer
        row = jax.lax.dynamic_index_in_dim(sendbuf, me, axis=0, keepdims=False)
        return row[None]

    return _wrap(mesh, axes, body, 3, 2)


def build_reduce(mesh, axes, root=0, reduce_op="sum"):
    """MPI_Reduce to ``root`` (reference ``collectives/1d/openmpi.py:142-155``):
    full reduction, result kept on root only (others zeroed)."""

    def body(x):
        total = _reduce_over(x, axes, reduce_op)
        keep = (_rank_id(axes) == root).astype(total.dtype)
        return total * keep

    return _wrap(mesh, axes, body, 2, 2)


def build_alltoall(mesh, axes, root=0):
    """MPI_Alltoall (reference ``collectives/1d/openmpi.py:157-171``):
    device i's slab [P, n] holds a chunk per peer; chunk j goes to rank j."""
    if len(axes) != 1:
        raise ValueError("alltoall requires a single mesh axis")

    def body(x):  # local [1, P, n]
        return jax.lax.all_to_all(x, axes[0], split_axis=1, concat_axis=1)

    return _wrap(mesh, axes, body, 3, 3)


def build_sendrecv(mesh, axes, root=0):
    """Ring sendrecv (reference ``collectives/1d/openmpi.py:173-198``:
    Isend to (rank+1)%P, Irecv from (rank-1)%P, waitall).  ``lax.ppermute``
    with the ring permutation lowers to neighbour ICI transfers."""
    if len(axes) != 1:
        raise ValueError("sendrecv ring requires a single mesh axis")
    num = mesh_num_ranks(mesh, axes)
    perm = [(i, (i + 1) % num) for i in range(num)]

    def body(x):  # local [1, n]
        return jax.lax.ppermute(x, axes[0], perm)

    return _wrap(mesh, axes, body, 2, 2)


def build_reducescatter(mesh, axes, root=0):
    """MPI_Reduce_scatter (not in the reference's 8 ops; the ZeRO-1 primitive
    — BASELINE.json config 5; reference ZeRO usage at ``test/ccl.py:86-89``)."""
    if len(axes) != 1:
        raise ValueError("reducescatter requires a single mesh axis")

    def body(x):  # local [1, P, n] -> [1, 1, n]
        out = jax.lax.psum_scatter(x[0], axes[0], scatter_dimension=0)  # [n]
        return out[None, None]

    return _wrap(mesh, axes, body, 3, 3)


# Compressed (quantised-wire) micro-ops, in one place like MATMUL_OPS:
# the runner's variant dispatch and the HLO audit's compressed targets
# both key off this tuple (docs/compression.md).
COMPRESSED_OPS = ("allreduce_q", "reducescatter_q")


def build_allreduce_q(mesh, axes, root=0, compression="int8",
                      accum_dtype=jnp.float32):
    """Quantised all-reduce: ring reduce-scatter in the wire dtype +
    all-gather of the quantised reduced chunks
    (``comm/compression.py::psum_compressed``).  Same [P, n] payload
    contract as ``allreduce``, so the sweep engine prices compressed vs
    fused on identical logical payloads; ``compression``/``accum_dtype``
    are the ``Variant.compression``/``Variant.accum_dtype`` knobs."""
    if len(axes) != 1:
        raise ValueError("allreduce_q requires a single mesh axis")
    from dlbb_tpu.comm.compression import psum_compressed

    def body(x):  # local [1, n]
        out = psum_compressed(
            x[0], axes[0], compression=compression, accum_dtype=accum_dtype
        )
        return out[None].astype(x.dtype)

    return _wrap(mesh, axes, body, 2, 2)


def build_reducescatter_q(mesh, axes, root=0, compression="int8",
                          accum_dtype=jnp.float32):
    """Quantised reduce-scatter: the ring phase of ``allreduce_q`` alone
    (``comm/compression.py::reduce_scatter_compressed``).  Same
    ``per_peer`` [P, P, n] payload contract as ``reducescatter``."""
    if len(axes) != 1:
        raise ValueError("reducescatter_q requires a single mesh axis")
    from dlbb_tpu.comm.compression import reduce_scatter_compressed

    def body(x):  # local [1, P, n] -> [1, 1, n]
        out = reduce_scatter_compressed(
            x[0], axes[0], compression=compression, accum_dtype=accum_dtype
        )
        return out[None, None].astype(x.dtype)

    return _wrap(mesh, axes, body, 3, 3)


def _synth_weight(rows: int, cols: int, dtype, row_offset=0, col_offset=0):
    """Deterministic dense weight generated ON DEVICE (broadcasted iota +
    cosine) — a host-side constant at these sizes would be embedded in the
    jitted program and stall compilation (see utils/timing.py).  The
    offsets select a shard of the logical global weight, so every rank's
    shard agrees with one global matrix and fused-vs-decomposed outputs
    are comparable bit-for-bit in tests."""
    i = jax.lax.broadcasted_iota(jnp.float32, (rows, cols), 0) + row_offset
    j = jax.lax.broadcasted_iota(jnp.float32, (rows, cols), 1) + col_offset
    return (jnp.cos(i * 0.37 + j * 0.11) / np.sqrt(rows)).astype(dtype)


# The collective-matmul micro-ops, in one place: the runner's variant
# dispatch and the HLO audit's per-schedule targets both key off this
# tuple, so registering a third matmul op cannot silently miss either.
MATMUL_OPS = ("ag_matmul", "matmul_rs")

_MICRO_SCHEDULES = ("fused", "ring", "bidir")


def _check_micro_schedule(schedule: str) -> None:
    if schedule not in _MICRO_SCHEDULES:
        raise ValueError(
            f"unknown collective-matmul schedule {schedule!r}; known: "
            f"{_MICRO_SCHEDULES}"
        )


def _require_3d_payload(op_name: str, x) -> None:
    """Global [P, B, S, H] payload gate for the collective-matmul ops —
    checked BEFORE shard_map so a flat 1D payload fails with a pointer at
    bench3d instead of a spec-arity error."""
    if x.ndim != 4:
        raise ValueError(
            f"{op_name} needs an LLM-shaped (B, S, H) payload — run it "
            "through the 3D sweep (bench3d / Sweep3D), not the flat 1D one"
        )


def build_ag_matmul(mesh, axes, root=0, schedule="fused"):
    """All-gather + matmul microbenchmark (the column-parallel TP
    projection in isolation; model dispatch in ``models/transformer.py``).

    Payload: per-rank ``[B, S, H]`` — this rank's sequence chunk.  Each
    rank multiplies the gathered ``[B, P*S, H]`` sequence by its column
    shard of a deterministic ``[H, H]`` weight, producing ``[B, P*S, H/P]``
    (same per-rank bytes as the input).

    ``schedule``: "fused" = one ``all_gather`` then the matmul (what GSPMD
    emits for the Megatron layout); "ring"/"bidir" = the decomposed
    overlapped schedule of ``parallel/collective_matmul.py`` — the sweep
    engine measures the two against each other via the ``overlap_ring`` /
    ``overlap_bidir`` variants.
    """
    if len(axes) != 1:
        raise ValueError("ag_matmul requires a single mesh axis")
    _check_micro_schedule(schedule)
    num = mesh_num_ranks(mesh, axes)

    def body(x):  # local [1, B, S, H] -> [1, B, P*S, H/P]
        xl = x[0]
        b, s, h = xl.shape
        if h % num != 0:
            raise ValueError(
                f"ag_matmul: hidden dim {h} not divisible by {num} ranks"
            )
        hp = h // num
        r = jax.lax.axis_index(axes[0])
        w = _synth_weight(h, hp, xl.dtype, col_offset=r * hp)
        if schedule == "fused":
            g = jax.lax.all_gather(xl, axes[0])        # [P, B, S, H]
            g = jnp.moveaxis(g, 0, 1).reshape(b, num * s, h)
            out = g @ w
        else:
            from dlbb_tpu.parallel.collective_matmul import _ag_matmul_body

            out = _ag_matmul_body(xl, w, axes[0], num,
                                  bidir=schedule == "bidir")
        return out[None]

    inner = _wrap(mesh, axes, body, 4, 4)

    def guarded(x):
        _require_3d_payload("ag_matmul", x)
        return inner(x)

    return jax.jit(guarded)


def build_matmul_rs(mesh, axes, root=0, schedule="fused"):
    """Matmul + reduce-scatter microbenchmark (the row-parallel TP
    projection in isolation).

    Payload: per-rank ``[B, S, H]`` — this rank's *feature* shard of a
    ``[B, S, P*H]`` activation.  Each rank multiplies by its row shard of
    a deterministic ``[P*H, H]`` weight and the partial products are
    reduce-scattered over the sequence dim to ``[B, S/P, H]`` chunks.

    ``schedule``: "fused" = local matmul + ``psum_scatter``; "ring"/
    "bidir" = the decomposed overlapped schedule.
    """
    if len(axes) != 1:
        raise ValueError("matmul_rs requires a single mesh axis")
    _check_micro_schedule(schedule)
    num = mesh_num_ranks(mesh, axes)

    def body(x):  # local [1, B, S, H] -> [1, B, S/P, H]
        xl = x[0]
        b, s, h = xl.shape
        if s % num != 0:
            raise ValueError(
                f"matmul_rs: sequence {s} not divisible by {num} ranks"
            )
        r = jax.lax.axis_index(axes[0])
        w = _synth_weight(h, h, xl.dtype, row_offset=r * h)
        if schedule == "fused":
            partial = xl @ w                            # [B, S, H]
            out = jax.lax.psum_scatter(
                partial, axes[0], scatter_dimension=1, tiled=True
            )                                           # [B, S/P, H]
        else:
            from dlbb_tpu.parallel.collective_matmul import _matmul_rs_body

            out = _matmul_rs_body(xl, w, axes[0], num,
                                  bidir=schedule == "bidir")
        return out[None]

    inner = _wrap(mesh, axes, body, 4, 4)

    def guarded(x):
        _require_3d_payload("matmul_rs", x)
        return inner(x)

    return jax.jit(guarded)


def build_barrier(mesh, axes, root=0):
    """Barrier analogue (reference ``collectives/1d/openmpi.py:60``:
    ``comm.Barrier()`` before each timed op).  In XLA's async-dispatch model a
    tiny psum + ``block_until_ready`` is the synchronisation point."""

    def body(x):  # local [1, 1]
        return jax.lax.psum(x, tuple(axes))

    return _wrap(mesh, axes, body, 2, 2)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

# Chain glue for chained timing: map output back to input shape with
# negligible work relative to the collective (values are irrelevant to
# timing; the dependency prevents loop-invariant hoisting).
def _chain_rescale(p: int):
    return lambda out: out * (1.0 / p)  # keep allreduce sums from blowing up


def _chain_take_first(p: int):
    return lambda out: out[:, 0]  # [P, P, *shape] -> [P, *shape]


def _chain_rebroadcast(p: int):
    def chain(out):  # [P, *shape] -> [P, P, *shape]
        return jnp.broadcast_to(out[:, None], (out.shape[0], p) + out.shape[1:])

    return chain


def _chain_scatter_back(p: int):
    def chain(out):  # reducescatter [P, 1, n] -> [P, P, n], rescaled
        tiled = jnp.broadcast_to(out, (out.shape[0], p) + out.shape[2:])
        return tiled * (1.0 / p)

    return chain


def _chain_ag_matmul(p: int):
    def chain(out):  # [P, B, P*S, H/P] -> [P, B, S, H] (local reshuffle)
        q, b, ps, hp = out.shape
        return out.reshape(q, b, ps // p, hp * p)

    return chain


def _chain_matmul_rs(p: int):
    def chain(out):  # [P, B, S/P, H] -> [P, B, S, H], damped (p-term sums)
        q, b, sp_, h = out.shape
        tiled = jnp.broadcast_to(out[:, :, None], (q, b, p, sp_, h))
        return tiled.reshape(q, b, p * sp_, h) * (1.0 / p)

    return chain


OPERATIONS: dict[str, CollectiveOp] = {
    "allreduce": CollectiveOp(
        "allreduce", "per_rank", "per_rank", build_allreduce, _chain_rescale
    ),
    "allgather": CollectiveOp(
        "allgather", "per_rank", "per_peer", build_allgather, _chain_take_first
    ),
    "broadcast": CollectiveOp(
        "broadcast", "per_rank", "per_rank", build_broadcast
    ),
    "gather": CollectiveOp(
        "gather", "per_rank", "per_peer", build_gather, _chain_take_first
    ),
    "scatter": CollectiveOp(
        "scatter", "per_peer", "per_rank", build_scatter, _chain_rebroadcast
    ),
    "reduce": CollectiveOp(
        "reduce", "per_rank", "per_rank", build_reduce, _chain_rescale
    ),
    "alltoall": CollectiveOp(
        "alltoall", "per_peer", "per_peer", build_alltoall
    ),
    "sendrecv": CollectiveOp(
        "sendrecv", "per_rank", "per_rank", build_sendrecv
    ),
    # reducescatter's [P, 1, n] output holds one reduced row per rank
    "reducescatter": CollectiveOp(
        "reducescatter", "per_peer", "per_rank", build_reducescatter,
        _chain_scatter_back,
    ),
    "allreduce_hierarchical": CollectiveOp(
        "allreduce_hierarchical", "per_rank", "per_rank",
        build_allreduce_hierarchical, _chain_rescale,
    ),
    # Collective-matmul micro-ops (docs/overlap.md): the TP projection
    # halves in isolation, 3D (B, S, H) payloads only.  The default build
    # is the FUSED schedule; the overlap_ring / overlap_bidir variants
    # (comm/variants.py) swap in the ring-decomposed schedule so the sweep
    # engine measures fused-vs-decomposed on identical payloads.
    "ag_matmul": CollectiveOp(
        "ag_matmul", "per_rank", "per_rank", build_ag_matmul,
        _chain_ag_matmul, transient_kind="per_peer",
    ),
    "matmul_rs": CollectiveOp(
        "matmul_rs", "per_rank", "per_rank", build_matmul_rs,
        _chain_matmul_rs, transient_kind="per_rank",
    ),
    # Quantised-wire collectives (docs/compression.md): the default build
    # is int8 with fp32 accumulation; the compress_* variants
    # (comm/variants.py) select fp8 / bf16-accum so the sweep engine
    # measures fused-vs-compressed on identical payloads.
    "allreduce_q": CollectiveOp(
        "allreduce_q", "per_rank", "per_rank", build_allreduce_q,
        _chain_rescale,
    ),
    "reducescatter_q": CollectiveOp(
        "reducescatter_q", "per_peer", "per_rank", build_reducescatter_q,
        _chain_scatter_back,
    ),
}


def get_op(name: str) -> CollectiveOp:
    try:
        return OPERATIONS[name]
    except KeyError:
        raise KeyError(
            f"unknown collective {name!r}; known: {sorted(OPERATIONS)}"
        ) from None


def payload_global_shape(
    op: CollectiveOp,
    mesh: Mesh,
    axes: Sequence[str],
    num_elements: int,
    shape: Optional[tuple[int, ...]] = None,
) -> tuple[int, ...]:
    """Global array shape ``make_payload`` would build, without building it."""
    num = mesh_num_ranks(mesh, axes)
    per_rank_shape = tuple(shape) if shape is not None else (num_elements,)
    if op.input_kind == "per_peer":
        return (num, num) + per_rank_shape
    return (num,) + per_rank_shape


def payload_aval(
    op: CollectiveOp,
    mesh: Mesh,
    axes: Sequence[str],
    num_elements: int,
    dtype=jnp.bfloat16,
    shape: Optional[tuple[int, ...]] = None,
) -> jax.ShapeDtypeStruct:
    """Abstract (shape, dtype, sharding) of the op's payload — what AOT
    lowering needs, so the compile-ahead scheduler
    (``dlbb_tpu.bench.schedule``) can compile a config's program on a
    background thread without materialising its (possibly GiB-scale)
    payload first."""
    global_shape = payload_global_shape(op, mesh, axes, num_elements, shape)
    target = jax.dtypes.canonicalize_dtype(dtype)
    sharding = NamedSharding(mesh, _specs(mesh, axes, len(global_shape)))
    return jax.ShapeDtypeStruct(global_shape, target, sharding=sharding)


def payload_cache_key(
    op: CollectiveOp,
    mesh: Mesh,
    axes: Sequence[str],
    num_elements: int,
    dtype=jnp.bfloat16,
    seed: int = DEFAULT_PAYLOAD_SEED,
    shape: Optional[tuple[int, ...]] = None,
) -> tuple:
    """Hashable identity of a ``make_payload`` result: two calls with equal
    keys return numerically identical, identically-sharded arrays, so sweep
    configs that share (shape, dtype, sharding) — e.g. every per-rank op at
    the same size label — can reuse one device payload instead of
    regenerating it per config."""
    global_shape = payload_global_shape(op, mesh, axes, num_elements, shape)
    target = jax.dtypes.canonicalize_dtype(dtype)
    return (
        op.input_kind,
        global_shape,
        jnp.dtype(target).name,
        seed,
        tuple(mesh.devices.shape),
        tuple(mesh.axis_names),
        tuple(axes),
        tuple(id(d) for d in mesh.devices.flat),
    )


def make_payload(
    op: CollectiveOp,
    mesh: Mesh,
    axes: Sequence[str],
    num_elements: int,
    dtype=jnp.bfloat16,
    seed: int = DEFAULT_PAYLOAD_SEED,
    shape: Optional[tuple[int, ...]] = None,
) -> jax.Array:
    """Build the global, mesh-sharded input for ``op``.

    Per-rank data is seeded ``seed + rank`` exactly like the reference
    (``collectives/1d/openmpi.py:247-248``, ``data_gen.py:37``).  ``shape``
    overrides the per-rank payload shape (3D benchmarks pass ``(B, S, H)``,
    reference ``collectives/3d/openmpi.py:21-23``); otherwise the payload is a
    flat ``[num_elements]`` vector as in the 1D benchmarks.
    """
    num = mesh_num_ranks(mesh, axes)
    per_rank_shape = tuple(shape) if shape is not None else (num_elements,)
    target = jax.dtypes.canonicalize_dtype(dtype)
    # Generate row-by-row in the target dtype: peak host memory stays at the
    # payload size itself (float32 staging is per-row only), which matters
    # for the 1 GB-label sweeps.
    rows = np.empty((num,) + per_rank_shape, dtype=target)
    for rank in range(num):
        rng = np.random.default_rng(seed + rank)
        rows[rank] = rng.standard_normal(per_rank_shape, dtype=np.float32)
    if op.input_kind == "per_peer":
        # every rank sends a distinct chunk to every peer: [P, P, *shape];
        # slab r is the rank rows cyclically shifted by r
        host = np.empty((num,) + rows.shape, dtype=target)
        idx = np.arange(num)
        for r in range(num):
            host[r] = rows[(idx - r) % num]
    else:
        host = rows
    sharding = NamedSharding(mesh, _specs(mesh, axes, host.ndim))
    return jax.device_put(host, sharding)
