"""Compressed collectives: quantised gradient reduction on the wire.

The tuning axis the rest of the framework sweeps (mesh shape, combiner
thresholds, overlap schedules — ``comm/variants.py``) only reorders *how*
bytes move; this module moves *fewer* bytes, following the compressed-SGD
line (Seide et al. 2014 1-bit SGD; Vogels et al. 2019 PowerSGD): quantise
the wire to int8 or fp8(e4m3), carry the quantisation error in an
error-feedback residual so training still converges.

Wire format (docs/compression.md)
---------------------------------
Chunked symmetric quantisation: the flat payload is split into
``SCALE_CHUNK_ELEMS``-element chunks; each chunk carries one fp32 scale
``amax(chunk) / qmax`` computed ON DEVICE (qmax = 127 for int8, 448 for
fp8 e4m3) and its values quantised to the wire dtype.  The scale tensor
is the side channel: it travels alongside every quantised hop and is
charged to the byte accounting (``analysis/expectations.py::
op_wire_bytes``; the comm-lint ceiling includes it).

Compressed reductions
---------------------
``psum_compressed`` is an all-to-all-free ring: quantise → ring
reduce-scatter in the wire dtype (each hop dequantises the incoming
partial into the accumulation dtype, adds the local chunk, re-quantises
for the next hop) → all-gather of the quantised reduced chunks →
dequantise.  ``reduce_scatter_compressed`` is the same ring without the
gather phase.  Both accept ``accum_dtype`` (fp32 default, bf16 variant)
— the bf16-vs-fp32 accumulation axis the sweep engine prices.

Error-feedback contract
-----------------------
The residual fed back by the train loop (``train/loop.py``) is the error
of the LOCAL quantiser: ``e ← c − D(Q(c))`` where ``c = grad + e_prev``
(:func:`quantization_error`).  Per-hop re-quantisation error inside the
ring is second-order (one extra rounding per hop on an already-quantised
partial) and is NOT fed back — documented, and bounded by the
``psum_compressed == psum`` tolerance tests (``tests/test_compression.py``).

Everything here is a *local* function meant to run inside ``shard_map``
(the global-array builders live in ``comm/ops.py``:
``build_allreduce_q`` / ``build_reducescatter_q``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

# The scale-chunk granularity is shared with the analytic wire model in
# dlbb_tpu/analysis/expectations.py (which must stay importable without
# jax — hence the constants live THERE and are imported here, not the
# other way around).
from dlbb_tpu.analysis.expectations import (
    COMPRESSIONS,
    SCALE_CHUNK_ELEMS,
)
from dlbb_tpu.compat import axis_size

# Symmetric quantisation ranges: int8 uses the full signed byte minus the
# asymmetric -128 (so the grid is symmetric around 0); fp8 e4m3's finite
# max is 448.
_QMAX = {"int8": 127.0, "fp8": 448.0}


def _wire_dtype(compression: str):
    if compression == "int8":
        return jnp.int8
    if compression == "fp8":
        return jnp.float8_e4m3fn
    raise ValueError(
        f"unknown compression {compression!r}; known: {COMPRESSIONS}"
    )


def check_compression(compression: str) -> str:
    """Validate (and return) a compression name — the one gate every
    entry point shares, so an unknown name fails with the known set."""
    _wire_dtype(compression)
    return compression


def quantize_chunked(
    x: jax.Array, compression: str = "int8",
) -> tuple[jax.Array, jax.Array]:
    """Chunked symmetric quantisation of a flat (last-axis) payload.

    Returns ``(q, scales)``: ``q`` is ``[..., n_chunks, SCALE_CHUNK_ELEMS]``
    in the wire dtype (zero-padded to a chunk multiple), ``scales`` is
    ``[..., n_chunks]`` fp32.  Scales are computed on device from the
    chunk amax — no host round-trip inside a timed region.
    """
    dtype = _wire_dtype(compression)
    qmax = _QMAX[compression]
    n = x.shape[-1]
    pad = (-n) % SCALE_CHUNK_ELEMS
    xf = x.astype(jnp.float32)
    if pad:
        widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        xf = jnp.pad(xf, widths)
    chunks = xf.reshape(xf.shape[:-1] + (-1, SCALE_CHUNK_ELEMS))
    amax = jnp.max(jnp.abs(chunks), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / qmax, 1.0)
    q = chunks / scale
    if compression == "int8":
        q = jnp.clip(jnp.round(q), -qmax, qmax)
    return q.astype(dtype), scale.squeeze(-1).astype(jnp.float32)


def dequantize_chunked(
    q: jax.Array, scales: jax.Array, num_elements: int,
    out_dtype=jnp.float32,
) -> jax.Array:
    """Inverse of :func:`quantize_chunked`: ``[..., n_chunks, C]`` wire
    payload + ``[..., n_chunks]`` scales → flat ``[..., num_elements]``
    (padding stripped) in ``out_dtype``."""
    x = q.astype(jnp.float32) * scales[..., None]
    x = x.reshape(x.shape[:-2] + (-1,))[..., :num_elements]
    return x.astype(out_dtype)


def _to_wire(q: jax.Array, compression: str) -> jax.Array:
    """Bitcast the quantised payload to a raw byte dtype for the
    collective.  XLA's float-normalization legalises fp8 arithmetic types
    to f16 on backends without native fp8 support (observed on this
    jaxlib's CPU backend) — which would silently DOUBLE the wire and trip
    the comm-lint byte ceiling.  int8 is a collective-native type on
    every backend; the bitcast costs nothing and pins the wire width."""
    if compression == "fp8":
        return lax.bitcast_convert_type(q, jnp.int8)
    return q


def _from_wire(w: jax.Array, compression: str) -> jax.Array:
    if compression == "fp8":
        return lax.bitcast_convert_type(w, jnp.float8_e4m3fn)
    return w


def quantization_error(x: jax.Array, compression: str = "int8") -> jax.Array:
    """``x − D(Q(x))`` — the local quantiser's error, which IS the
    error-feedback residual the train loop carries in optimizer state
    (the Seide-style compressor-error estimate; see module docstring for
    why hop re-quantisation error is excluded)."""
    q, s = quantize_chunked(x, compression)
    return (x.astype(jnp.float32)
            - dequantize_chunked(q, s, x.shape[-1], jnp.float32)
            ).astype(x.dtype)


def _ring_reduce(
    local_chunk: Callable[[int], jax.Array],
    axis_name: str,
    p: int,
    compression: str,
    accum_dtype,
) -> jax.Array:
    """The shared quantised accumulating ring.

    ``local_chunk(s)`` must return this device's contribution for the
    travelling accumulator at unrolled step ``s`` (the accumulator keeps
    its chunk identity as it moves: the chunk that ends on this device
    visits every rank exactly once).  Each hop ppermutes the quantised
    partial AND its scale tensor (two collective-permutes per hop — the
    scale side channel is real wire traffic and is audited as such).

    Hops run under ``qring_hop*`` named scopes: unlike the collective-
    matmul ``ring_hop`` hops, this ring is *deliberately* sequential
    (each hop's dequant-accumulate-requant feeds the next), so the
    schedule auditor must be able to tell them apart — qring hops are
    exempt from the serialized-collective overlap gate.
    """
    fwd = [(i, (i + 1) % p) for i in range(p)]
    part = local_chunk(0).astype(accum_dtype)
    for s in range(1, p):
        q, scales = quantize_chunked(part, compression)
        with jax.named_scope(f"qring_hop{s}"):
            q = _from_wire(
                lax.ppermute(_to_wire(q, compression), axis_name, fwd),
                compression,
            )
            scales = lax.ppermute(scales, axis_name, fwd)
        incoming = dequantize_chunked(
            q, scales, part.shape[-1], accum_dtype
        )
        part = incoming + local_chunk(s).astype(accum_dtype)
    return part


def psum_compressed(
    x: jax.Array,
    axis_name: str,
    compression: str = "int8",
    accum_dtype=jnp.float32,
) -> jax.Array:
    """Quantised all-reduce over ``axis_name`` (call inside shard_map).

    Ring reduce-scatter in the wire dtype, then an all-gather of the
    quantised reduced chunks — total wire ≈ ``2(P−1)/P × n`` wire-dtype
    bytes + scales, vs ``2(P−1)/P × n × 2`` for the bf16 ring all-reduce
    the audit uses as its baseline.  Output has ``x``'s shape and dtype;
    accumulation runs in ``accum_dtype``.
    """
    check_compression(compression)
    p = axis_size(axis_name)
    if p == 1:
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    n = flat.shape[0]
    c = -(-n // p)  # ring-chunk elements
    if p * c != n:
        flat = jnp.pad(flat, (0, p * c - n))
    chunks = flat.reshape(p, c)
    r = lax.axis_index(axis_name)
    # init with chunk (r-1): the accumulator that ends here is chunk r,
    # so the gathered rows below land in order (row k == chunk k)
    part = _ring_reduce(
        lambda s: lax.dynamic_index_in_dim(
            chunks, (r - 1 - s) % p, axis=0, keepdims=False),
        axis_name, p, compression, accum_dtype,
    )
    q, scales = quantize_chunked(part, compression)
    gq = _from_wire(
        lax.all_gather(_to_wire(q, compression), axis_name), compression,
    )                                          # [P, n_chunks, C] wire dtype
    gs = lax.all_gather(scales, axis_name)     # [P, n_chunks] fp32
    rows = dequantize_chunked(gq, gs, c, accum_dtype)  # [P, c]
    out = rows.reshape(-1)[:n].reshape(orig_shape)
    return out.astype(orig_dtype)


def reduce_scatter_compressed(
    rows: jax.Array,
    axis_name: str,
    compression: str = "int8",
    accum_dtype=jnp.float32,
) -> jax.Array:
    """Quantised reduce-scatter (call inside shard_map).

    ``rows`` is this device's ``[P, *chunk]`` slab — row ``k`` is the
    contribution destined to rank ``k`` (the registry's ``per_peer``
    layout).  Returns this rank's fully-reduced chunk; wire is the ring
    phase of :func:`psum_compressed` alone: ``(P−1)`` hops of one
    wire-dtype chunk + scales.
    """
    check_compression(compression)
    p = axis_size(axis_name)
    chunk_shape = rows.shape[1:]
    if rows.shape[0] != p:
        raise ValueError(
            f"reduce_scatter_compressed: leading dim {rows.shape[0]} must "
            f"equal the axis size {p}"
        )
    if p == 1:
        return rows[0]
    flat_rows = rows.reshape(p, -1)
    n = flat_rows.shape[-1]
    r = lax.axis_index(axis_name)
    part = _ring_reduce(
        lambda s: lax.dynamic_index_in_dim(
            flat_rows, (r - 1 - s) % p, axis=0, keepdims=False),
        axis_name, p, compression, accum_dtype,
    )
    return part[:n].reshape(chunk_shape).astype(rows.dtype)
