"""Training loop: DDP gradient all-reduce + ZeRO-1 optimizer-state sharding
(parity with the reference's only backward path, ``test/ccl.py:59-117``
DeepSpeed ZeRO; BASELINE.json configs 4-5)."""

from dlbb_tpu.train.loop import TrainState, make_train_step, run_train

__all__ = ["TrainState", "make_train_step", "run_train"]
