"""DDP / ZeRO-{1,2,3} training loop.

The reference's training capability is a DeepSpeed smoke: ZeRO-2 engine init,
MSE loss, ``backward()`` (gradient all-reduce / reduce-scatter) and
``step()`` (``test/ccl.py:59-117``), plus ZeRO-0 + Adam (``test/ds_mpi_test.py``).
TPU-native re-design — every stage is a *sharding declaration*, not a
hand-written collective schedule:

- **DDP (stage 0)**: batch sharded over the ``dp`` mesh axis, params
  replicated over ``dp`` (and TP-sharded over ``tp``); the gradient
  all-reduce the reference delegates to DeepSpeed/oneCCL is inserted by XLA
  GSPMD because the loss mean contracts a dp-sharded batch against
  dp-replicated params.
- **ZeRO-1**: optimizer state (Adam mu/nu) sharded over ``dp`` on top of the
  TP layout.  Declaring sharded out-shardings for the optimizer state makes
  XLA lower the grad all-reduce into reduce-scatter + sharded update +
  all-gather of the new params — the ZeRO-1 dataflow of
  BASELINE.json config 5 — without hand-written collectives.
- **ZeRO-2**: additionally pins the *gradients* to the dp-sharded layout with
  a sharding constraint, so the backward's grad buffers are reduce-scattered
  as they are produced (sharded grad memory — DeepSpeed stage-2 semantics,
  the config at reference ``test/ccl.py:86-89``).
- **ZeRO-3 / FSDP**: the parameters themselves live dp-sharded; XLA inserts
  the per-layer all-gathers on use in forward/backward and frees the
  gathered copies after — DeepSpeed stage-3 dataflow, declared in one spec
  tree.
- Adam via optax; MSE loss vs a fixed target batch (parity with
  ``test/ccl.py:110``).
"""

from __future__ import annotations

import os
import signal
import time
import warnings
from pathlib import Path
from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlbb_tpu.data.synthetic import create_dataset_from_config
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.parallel.plan import ParallelismPlan
from dlbb_tpu.models.sharding import batch_spec, param_specs, specs_for_mesh
from dlbb_tpu.models.transformer import (
    forward,
    forward_flops,
    init_params_sharded,
)
from dlbb_tpu.obs import spans
from dlbb_tpu.utils.config import load_config, save_json
from dlbb_tpu.utils.metrics import Timer, summarize
from dlbb_tpu.utils.profiling import annotate, step_annotation
from dlbb_tpu.utils.sysinfo import collect_system_info
from dlbb_tpu.utils.timing import resolve_timing_mode, time_fn_chained


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def _is_spec(x) -> bool:
    return isinstance(x, P)


def _dp_shard_spec(spec: P, shape: tuple[int, ...], dp_size: int,
                   dp_axis: str = "dp") -> P:
    """Add a ``dp`` sharding to ``spec`` on the largest unsharded,
    dp-divisible axis (ZeRO optimizer-state / gradient / FSDP-param
    partitioning).  No-op when ``spec`` already uses ``dp`` or no axis
    divides evenly."""
    parts = list(spec) + [None] * (len(shape) - len(spec))
    if any(dp_axis in (ax if isinstance(ax, tuple) else (ax,))
           for ax in parts if ax is not None):
        return spec
    candidates = sorted(
        (i for i in range(len(shape))
         if parts[i] is None and shape[i] % dp_size == 0 and shape[i] > 1),
        key=lambda i: -shape[i],
    )
    if not candidates:
        return spec
    parts[candidates[0]] = dp_axis
    return P(*parts)


def dp_sharded_param_specs(params: Any, dp_size: int,
                           dp_axis: str = "dp",
                           base_specs: Any = None) -> Any:
    """The TP (or TP+PP) spec tree with a ``dp`` sharding added per leaf —
    the FSDP / ZeRO-3 parameter layout, also the ZeRO-{1,2}
    optimizer-state/grad layout."""
    if base_specs is None:
        base_specs = param_specs()
    return jax.tree.map(
        lambda s, p: _dp_shard_spec(s, p.shape, dp_size, dp_axis),
        base_specs, params, is_leaf=_is_spec,
    )


def opt_state_specs(params: Any, opt_state: Any, zero1: bool,
                    dp_size: int, base_specs: Any = None) -> Any:
    """Partition specs for the optimizer-state pytree.

    Optax state subtrees that mirror the param pytree (Adam mu/nu) are
    detected *structurally* — any subtree with the params' treedef AND
    leafwise-matching shapes gets the params' spec tree (treedef matching
    alone would misfire on adafactor's v_row/v_col/v subtrees, which mirror
    the params' structure with factored lower-rank statistics; pure shape
    matching would collide when two params share a shape with different TP
    layouts, e.g. ffn_intermediate == hidden_size).  Everything else —
    step counts, empty states, factored adafactor statistics (sublinear in
    parameter count, so ZeRO sharding is moot for them) — stays replicated.
    """
    p_def = jax.tree.structure(params)
    p_shapes = [getattr(p, "shape", None) for p in jax.tree.leaves(params)]
    if base_specs is None:
        base_specs = param_specs()
    spec_for_params = (
        dp_sharded_param_specs(params, dp_size, base_specs=base_specs)
        if zero1 else base_specs
    )

    def recur(node):
        try:
            if jax.tree.structure(node) == p_def and all(
                getattr(leaf, "shape", None) == shape
                for leaf, shape in zip(jax.tree.leaves(node), p_shapes)
            ):
                return spec_for_params
        except Exception:  # noqa: BLE001 — unhashable/exotic nodes
            pass
        if isinstance(node, tuple):  # incl. optax NamedTuple states
            children = [recur(c) for c in node]
            if hasattr(node, "_fields"):  # NamedTuple: positional ctor
                return type(node)(*children)
            return tuple(children)
        if isinstance(node, list):
            return [recur(c) for c in node]
        if isinstance(node, dict):
            return {k: recur(v) for k, v in node.items()}
        return P()  # scalar leaves (adam count) and unknown leaves: replicated

    return recur(opt_state)


def mse_loss(params, batch, targets, config: ModelConfig,
             mesh: Optional[Mesh] = None,
             num_microbatches: Optional[int] = None,
             moe_aux_weight: float = 0.0) -> jax.Array:
    """MSE vs the target batch (parity with ``test/ccl.py:110``), plus the
    weighted MoE load-balancing loss when requested
    (``training.moe_aux_loss_weight``)."""
    if moe_aux_weight > 0.0:
        pred, aux = forward(params, batch, config, mesh=mesh,
                            num_microbatches=num_microbatches,
                            with_aux=True)
    else:
        pred = forward(params, batch, config, mesh=mesh,
                       num_microbatches=num_microbatches)
        aux = 0.0
    mse = jnp.mean(
        (pred.astype(jnp.float32) - targets.astype(jnp.float32)) ** 2
    )
    return mse + moe_aux_weight * aux


def resolve_zero_stage(zero1: bool = False,
                       zero_stage: Optional[int] = None) -> int:
    """Collapse the legacy ``zero1`` flag and the explicit ``zero_stage``
    into one stage number 0-3."""
    if zero_stage is not None:
        if zero_stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_stage must be 0-3, got {zero_stage}")
        return zero_stage
    return 1 if zero1 else 0


MODE_NAMES = {0: "ddp", 1: "zero1", 2: "zero2", 3: "zero3"}

# Approximate per-parameter update FLOPs for the utilisation accounting
# (elementwise moment updates + bias correction + apply; small vs the 3x
# forward term for any real model).
OPTIMIZER_FLOPS_PER_PARAM = {"adam": 18, "adamw": 22, "sgd": 6,
                             "adafactor": 14}


def make_train_step(
    config: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    params: Any,
    zero1: bool = False,
    zero_stage: Optional[int] = None,
    num_microbatches: Optional[int] = None,
    moe_aux_weight: float = 0.0,
    grad_accum: int = 1,
    pipeline_schedule: str = "gpipe",
    grad_compression: str = "none",
    compression_accum: str = "float32",
    residual_dtype: Any = None,
):
    """Build (jitted step fn, initial sharded TrainState) for the given
    ZeRO stage (0=DDP, 1=opt-state sharding, 2=+grad sharding, 3=FSDP).
    A mesh with a >1-sized ``pp`` axis makes the inner forward pipelined
    (``num_microbatches`` microbatches, default one per stage);
    ``pipeline_schedule`` picks the training schedule there — "gpipe"
    (autodiff through the forward pipeline) or "1f1b" (interleaved
    backward, activation live-range O(pp) — ``parallel/pipeline.py``);
    ``moe_aux_weight`` adds the MoE load-balancing loss; ``grad_accum``
    splits the batch into that many sequential micro-steps whose mean
    gradient feeds one optimizer update (same numerics as the full batch
    for mean losses, 1/grad_accum the activation memory).

    ``grad_compression`` ("int8"/"fp8", docs/compression.md) swaps the
    dp gradient reduction for the quantised ring of
    ``comm/compression.py``: local grads are computed inside a
    full-manual shard_map (no GSPMD all-reduce exists to begin with),
    the error-feedback residual is added, and the compressed
    ``psum_compressed`` reduces on an int8/fp8 wire.  The residual lives
    as an extra optimizer-state leaf
    (``train/optim.py::GradCompressionState`` — dp-sharded, checkpointed,
    stored in ``residual_dtype``); ``compression_accum`` picks the ring's
    accumulation precision.  Supported envelope: pure-dp meshes (every
    other axis size 1), ZeRO stages 0/2, dense attention, no grad
    accumulation / MoE aux loss — violations raise here, at build time.

    The returned step donates its state argument, and the ``device_put``
    here may alias the caller's ``params`` buffers — treat the input
    ``params`` pytree as consumed once the first step has run."""
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if pipeline_schedule not in ("gpipe", "1f1b"):
        raise ValueError(
            f"unknown pipeline_schedule {pipeline_schedule!r} "
            "(expected 'gpipe' or '1f1b')"
        )
    pp_size = mesh.shape.get("pp", 1)
    if pipeline_schedule == "1f1b" and pp_size <= 1:
        raise ValueError(
            "pipeline_schedule='1f1b' requires parallelism.pipeline_parallel"
            " > 1 (it is a pipeline training schedule)"
        )
    stage = resolve_zero_stage(zero1, zero_stage)
    dp_size = mesh.shape.get("dp", 1)
    from dlbb_tpu.train.optim import (
        GRAD_COMPRESSIONS,
        GradCompressionState,
        init_error_feedback,
    )

    if grad_compression not in GRAD_COMPRESSIONS:
        raise ValueError(
            f"unknown grad_compression {grad_compression!r}; known: "
            f"{GRAD_COMPRESSIONS}"
        )
    compression_on = grad_compression != "none"
    if compression_on:
        # the compressed path computes LOCAL grads inside a full-manual
        # shard_map and owns the reduction; every capability outside that
        # envelope is rejected at build time, not at trace time
        other = [a for a in mesh.axis_names
                 if a != "dp" and mesh.shape[a] > 1]
        if other:
            raise ValueError(
                "training.grad_compression requires a pure data-parallel "
                f"mesh; axes {other} have size > 1 (compose compression "
                "with tp/sp/pp is future work — docs/compression.md)"
            )
        if dp_size <= 1:
            raise ValueError(
                "training.grad_compression with data_parallel=1 has no "
                "gradient reduction to compress: the ring is an identity, "
                "so the error-feedback residual would subtract a "
                "quantisation error that was never incurred — run "
                "uncompressed, or use a dp>1 mesh"
            )
        if stage not in (0, 2):
            raise ValueError(
                "training.grad_compression supports ZeRO stages 0 (DDP) "
                f"and 2 (grad sharding), not stage {stage}: stages 1/3 "
                "shard the optimizer update itself, which the compressed "
                "replicated-update path does not compose with"
            )
        # NOTE stage 2 + compression trades ZeRO-2's grad-MEMORY saving
        # for the wire saving: the ring's gather phase transiently
        # materialises the replicated flat gradient on every rank (DDP
        # peak) before the layout pin slices it back to shards — a
        # sharded-update path on reduce_scatter_compressed alone is the
        # future-work alternative (docs/compression.md)
        if grad_accum != 1:
            raise ValueError(
                "training.grad_compression does not compose with "
                "gradient_accumulation yet (accumulate locally before "
                "one compressed reduction is future work)"
            )
        if moe_aux_weight != 0.0:
            raise ValueError(
                "training.grad_compression does not support the MoE aux "
                "loss (expert-parallel compression is future work)"
            )
        if config.attention not in ("full", "simplified", "dense"):
            raise ValueError(
                f"training.grad_compression requires a dense attention "
                f"mode (full/simplified/dense), got "
                f"{config.attention!r}: shard_map attention modes nest "
                "their own manual meshes"
            )
    base_specs = specs_for_mesh(mesh, moe=config.is_moe)
    dp_specs = dp_sharded_param_specs(params, dp_size, base_specs=base_specs)
    p_spec_tree = dp_specs if stage >= 3 else base_specs
    p_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), p_spec_tree, is_leaf=_is_spec
    )
    params = jax.device_put(params, p_shardings)
    opt_state = optimizer.init(params)
    s_specs = opt_state_specs(params, opt_state, stage >= 1, dp_size,
                              base_specs=base_specs)
    s_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), s_specs, is_leaf=_is_spec
    )
    opt_state = jax.device_put(opt_state, s_shardings)
    if compression_on:
        # error-feedback residual rides as an optimizer-state leaf: one
        # [1, total_params] row per dp rank (P("dp") — per-device memory
        # is 1x the flat grads, never replicated), checkpointed with the
        # rest of the state, stored in residual_dtype (= moments_dtype
        # under the memory-reduced-Adam convention)
        res_dtype = jnp.dtype(residual_dtype) if residual_dtype is not None \
            else jnp.float32
        comp_shardings = GradCompressionState(
            residual=NamedSharding(mesh, P("dp"))
        )
        comp = init_error_feedback(params, dp_size, res_dtype,
                                   sharding=comp_shardings.residual)
        opt_state = (opt_state, comp)
        s_shardings = (s_shardings, comp_shardings)
    state = TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    state_shardings = TrainState(
        p_shardings, s_shardings, NamedSharding(mesh, P())
    )
    grad_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), dp_specs, is_leaf=_is_spec
    )

    if pipeline_schedule == "1f1b":
        from dlbb_tpu.parallel.pipeline import pipeline_1f1b_grads

        def value_and_grads(params, batch, targets):
            return pipeline_1f1b_grads(
                params, batch, targets, config, mesh,
                num_microbatches=num_microbatches,
                moe_aux_weight=moe_aux_weight,
            )
    else:
        def value_and_grads(params, batch, targets):
            return jax.value_and_grad(mse_loss)(
                params, batch, targets, config, mesh, num_microbatches,
                moe_aux_weight,
            )

    def loss_and_grads(params, batch, targets):
        if grad_accum == 1:
            return value_and_grads(params, batch, targets)
        b = batch.shape[0]
        if b % grad_accum != 0:
            raise ValueError(
                f"batch_size={b} not divisible by grad_accum={grad_accum}"
            )
        if (b // grad_accum) % dp_size != 0:
            if config.attention in ("full", "simplified"):
                # dense attention: numerics stay exact — GSPMD reshards
                # each micro-batch onto the dp axis — but the layout churn
                # costs collectives, so surface it without rejecting
                warnings.warn(
                    f"micro-batch size {b // grad_accum} (batch_size={b} / "
                    f"grad_accum={grad_accum}) not divisible by "
                    f"dp={dp_size}; each micro-step reshards the batch "
                    "instead of keeping the dp layout (correct but "
                    "slower — measured pair: results/parallelism/"
                    "train_ddp_ga2_{divisible_b16,reshard_b20}.json, "
                    "per-token throughput in "
                    "stats/parallelism/PARALLELISM.md)",
                    stacklevel=2,
                )
            else:
                # flash/ring/ulysses shard_map the batch dim over dp
                # explicitly and cannot reshard — reject with a clear error
                # instead of letting shard_map fail cryptically at trace
                raise ValueError(
                    f"micro-batch size {b // grad_accum} (batch_size={b} / "
                    f"grad_accum={grad_accum}) not divisible by "
                    f"dp={dp_size}: attention={config.attention!r} "
                    "partitions the batch over dp inside shard_map and "
                    "cannot reshard a smaller micro-batch"
                )
        mb = batch.reshape(grad_accum, b // grad_accum, *batch.shape[1:])
        mt = targets.reshape(grad_accum, b // grad_accum, *targets.shape[1:])

        def acc(carry, xs):
            loss_sum, g_sum = carry
            x, t = xs
            loss, g = value_and_grads(params, x, t)
            if stage >= 2:
                # keep every micro-step's grads (and thus the carry) in
                # the dp-sharded layout, so accumulation never materialises
                # a replicated full-size gradient pytree under ZeRO-2/3
                g = jax.lax.with_sharding_constraint(g, grad_shardings)
            # accumulate in fp32 regardless of params dtype — bf16 sums
            # would round each micro-step and break full-batch equivalence
            g_sum = jax.tree.map(
                lambda s, gi: s + gi.astype(jnp.float32), g_sum, g
            )
            return (loss_sum + loss, g_sum), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        if stage >= 2:
            zeros = jax.lax.with_sharding_constraint(zeros, grad_shardings)
        (loss_sum, g_sum), _ = jax.lax.scan(
            acc, (jnp.zeros((), jnp.float32), zeros), (mb, mt)
        )
        inv = 1.0 / grad_accum
        grads = jax.tree.map(
            lambda g, p: (g * inv).astype(p.dtype), g_sum, params
        )
        return loss_sum * inv, grads

    if compression_on:
        from jax.flatten_util import ravel_pytree

        from dlbb_tpu.comm.compression import (
            psum_compressed,
            quantization_error,
        )
        from dlbb_tpu.compat import shard_map

        accum = (jnp.bfloat16 if compression_accum == "bfloat16"
                 else jnp.float32)
        bspec = batch_spec(mesh)
        # params enter the shard_map replicated (full value per device:
        # every non-dp axis is size 1 and params are dp-replicated)
        local_p_specs = jax.tree.map(lambda _: P(), params)

        def _compressed_body(p, b, t, res):
            # local loss/grads: the batch shard never crosses dp here, so
            # no GSPMD gradient all-reduce exists to begin with — the
            # ONLY gradient reduction is the quantised ring below
            loss, g = jax.value_and_grad(mse_loss)(
                p, b, t, config, None, None, 0.0
            )
            flat_g, unravel = ravel_pytree(g)
            c = flat_g.astype(jnp.float32) + res[0].astype(jnp.float32)
            reduced = psum_compressed(
                c, "dp", compression=grad_compression, accum_dtype=accum
            ) / dp_size
            # Seide-style error feedback: carry the LOCAL quantiser's
            # error into the next step (docs/compression.md)
            new_res = quantization_error(c, grad_compression)
            loss = jax.lax.psum(loss, "dp") / dp_size
            return (loss, unravel(reduced.astype(flat_g.dtype)),
                    new_res.astype(res.dtype)[None])

        compressed_loss_and_grads = shard_map(
            _compressed_body, mesh=mesh,
            in_specs=(local_p_specs, bspec, bspec, P("dp")),
            out_specs=(P(), local_p_specs, P("dp")),
            # the ppermute ring defeats static replication inference for
            # the replicated outputs; correctness is pinned by
            # tests/test_compression.py (psum_compressed == psum)
            check_vma=False,
        )

        def step(state: TrainState, batch, targets):
            inner_state, comp = state.opt_state
            loss, grads, new_res = compressed_loss_and_grads(
                state.params, batch, targets, comp.residual
            )
            if stage >= 2:
                # the reduction wire is already compressed; the ZeRO-2
                # layout pin keeps grad memory dp-sharded downstream
                # (replicated -> sharded is a local slice, no collective)
                grads = jax.lax.with_sharding_constraint(
                    grads, grad_shardings)
            updates, new_inner = optimizer.update(
                grads, inner_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(
                new_params,
                (new_inner, GradCompressionState(residual=new_res)),
                state.step + 1,
            ), loss
    else:
        def step(state: TrainState, batch, targets):
            loss, grads = loss_and_grads(state.params, batch, targets)
            if stage >= 2:
                # pin grads to the dp-sharded layout: the dp all-reduce
                # lowers to reduce-scatter and grad memory stays sharded
                # (ZeRO-2)
                grads = jax.lax.with_sharding_constraint(
                    grads, grad_shardings)
            updates, new_opt = optimizer.update(
                grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(new_params, new_opt, state.step + 1), loss

    jit_step = jax.jit(
        step,
        in_shardings=(state_shardings, NamedSharding(mesh, batch_spec(mesh)),
                      NamedSharding(mesh, batch_spec(mesh))),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    return jit_step, state


def run_train(
    config: dict[str, Any],
    zero1: bool = False,
    zero_stage: Optional[int] = None,
    devices: Optional[Sequence] = None,
    output_dir: Optional[str] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """Config-driven training benchmark (the train-side analogue of the E2E
    forward harness; reference flow ``test/ccl.py:59-117``)."""
    # explicit caller args (zero_stage or legacy zero1) win over the config
    if zero_stage is None and not zero1 \
            and "zero_stage" in config.get("training", {}):
        zero_stage = config["training"]["zero_stage"]
    stage = resolve_zero_stage(zero1, zero_stage)

    model_cfg = ModelConfig.from_dict(config["model"])
    plan = ParallelismPlan.from_config(config, model_cfg, devices)
    mesh, num_microbatches = plan.mesh, plan.num_microbatches
    inp = config["input"]
    dtype = jnp.bfloat16 if model_cfg.dtype == "bfloat16" else jnp.float32
    data = create_dataset_from_config(
        config, mesh=mesh, spec=batch_spec(mesh), dtype=dtype,
        hidden_size=model_cfg.hidden_size,
    )
    targets = create_dataset_from_config(
        config, mesh=mesh, spec=batch_spec(mesh), dtype=dtype,
        hidden_size=model_cfg.hidden_size, seed_offset=1,
    )

    train_cfg = config.get("training", {})
    lr = train_cfg.get("learning_rate", 1e-3)
    moe_aux_weight = float(train_cfg.get("moe_aux_loss_weight", 0.0))
    if moe_aux_weight > 0.0 and not model_cfg.is_moe:
        raise ValueError(
            "training.moe_aux_loss_weight requires a MoE model "
            "(model.num_experts > 0)"
        )
    grad_accum = int(train_cfg.get("gradient_accumulation", 1))
    if grad_accum > 1:
        bs = inp["batch_size"]
        if bs % grad_accum != 0:
            raise ValueError(
                f"batch_size={bs} not divisible by "
                f"gradient_accumulation={grad_accum}"
            )
        if plan.pp > 1:
            # training feeds batch/grad_accum rows to each pipelined
            # micro-step, so the microbatch schedule must also divide the
            # accumulation micro-batch — a training-only constraint, checked
            # here (not in the shared plan) so forward-only harnesses that
            # reuse a training config are unaffected
            from dlbb_tpu.parallel.pipeline import validate_pipeline

            validate_pipeline(model_cfg, plan.pp, bs // grad_accum,
                              plan.num_microbatches)
    from dlbb_tpu.train.optim import (
        build_optimizer,
        compression_accum_dtype,
        moments_dtype,
        resolve_grad_compression,
        resolve_names,
    )

    optimizer = build_optimizer(train_cfg)
    opt_name, sched_name = resolve_names(train_cfg)
    grad_compression = resolve_grad_compression(train_cfg)
    comp_accum = compression_accum_dtype(train_cfg)

    pipeline_schedule = str(train_cfg.get("pipeline_schedule", "gpipe"))
    params = init_params_sharded(
        model_cfg, jax.random.key(inp.get("seed", 42)), mesh
    )
    jit_step, state = make_train_step(
        model_cfg, mesh, optimizer, params, zero_stage=stage,
        num_microbatches=num_microbatches, moe_aux_weight=moe_aux_weight,
        grad_accum=grad_accum, pipeline_schedule=pipeline_schedule,
        grad_compression=grad_compression, compression_accum=comp_accum,
        # the residual follows the moments-storage convention: bf16/fp16
        # moments => bf16/fp16 residual (memory-reduced Adam)
        residual_dtype=moments_dtype(train_cfg),
    )
    # make_train_step may have resharded params into fresh buffers (ZeRO-3);
    # at 13B scale the caller's copy is tens of GB of dead weight on the
    # host simulating the mesh — drop the reference before the step runs
    del params

    # Checkpoint / resume (no reference analogue — SURVEY §5.4 "none"; see
    # dlbb_tpu/train/checkpoint.py).  Resume happens before warmup so the
    # restored step counter carries through the run.
    ckpt = None
    resumed_from = None
    if "checkpoint" in train_cfg \
            and train_cfg["checkpoint"].get("enabled", True):
        from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer

        ckpt = Checkpointer(CheckpointConfig.from_dict(train_cfg["checkpoint"]))
        resumed_from = ckpt.latest_step()
        state = ckpt.restore_or(state)

    execution = config.get("execution", {})
    warmup = execution.get("warmup_iterations", 2)
    iters = execution.get("benchmark_iterations", 10)
    mode = resolve_timing_mode("auto")

    batch, tgt = data.get_batch(), targets.get_batch()
    # variant-tuned XLA compilation (e.g. the "nofuse" combiner-passes-off
    # variant, dlbb_tpu/comm/variants.py) — per-computation compiler options
    # need no process relaunch, unlike XLA_FLAGS
    comp_opts = {
        str(k): str(v)
        for k, v in (execution.get("compiler_options") or {}).items()
    }
    with spans.span("compile+warmup", cat="train"), \
            annotate("compile+warmup"):
        t0 = time.perf_counter()
        if comp_opts and mode == "per_iter":
            # AOT-compile with the options; in chained mode the options are
            # instead applied to the outer timing loop (an AOT executable
            # cannot be traced inside it)
            jit_step = jit_step.lower(state, batch, tgt).compile(
                compiler_options=comp_opts
            )
        state, loss = jit_step(state, batch, tgt)
        float(loss)  # forces completion on any backend
        compile_time = time.perf_counter() - t0
        for _ in range(max(0, warmup - 1)):
            state, loss = jit_step(state, batch, tgt)
            float(loss)  # forces completion on any backend

    # Graceful preemption (docs/resilience.md): SIGTERM between steps
    # breaks the loop and falls through to the forced final checkpoint
    # save below — the TPU-fleet preemption notice becomes a clean
    # resume point instead of a mid-step kill.  The `preempt` fault site
    # (dlbb_tpu.resilience.inject) drives the same path in the chaos gate.
    from dlbb_tpu.resilience import PreemptionGuard, inject

    losses = []
    preempted_at: Optional[int] = None
    with PreemptionGuard() as guard:
        if mode == "per_iter":
            step_times = []
            for i in range(iters):
                if inject.fire("preempt"):
                    os.kill(os.getpid(), signal.SIGTERM)
                if guard.requested:
                    preempted_at = int(jax.device_get(state.step))
                    break
                # span + device annotation wrap the Timer from the
                # OUTSIDE — nothing profiler-shaped inside the timed
                # region (the profiler-in-timed-region lint contract)
                with spans.span("train_step", cat="train", step=i), \
                        step_annotation("train_step", i):
                    with Timer() as t:
                        state, loss = jit_step(state, batch, tgt)
                        jax.block_until_ready(loss)
                    step_times.append(t.elapsed)
                losses.append(float(loss))
                if ckpt is not None:
                    ckpt.maybe_save(state)
            timing_meta = {
                "timing_mode": "per_iter",
                "timing_method":
                    "time.perf_counter() + jax.block_until_ready()",
            }
        else:
            # optimisation trajectory first (each float(loss) forces
            # completion, so losses are real), then honest chained step
            # timing
            for _ in range(iters):
                if inject.fire("preempt"):
                    os.kill(os.getpid(), signal.SIGTERM)
                if guard.requested:
                    preempted_at = int(jax.device_get(state.step))
                    break
                state, loss = jit_step(state, batch, tgt)
                losses.append(float(loss))
                if ckpt is not None:
                    ckpt.maybe_save(state)

            if preempted_at is None:
                def timed_step(b, t, st):
                    new_state, _ = jit_step(st, b, t)
                    return new_state

                with spans.span("measure", cat="train"), \
                        annotate("measure"):
                    # state is donated to the timing loop (halves resident
                    # TrainState HBM — decisive for Adam at 1B on the
                    # 16 GiB chip); the returned carry IS the post-timing
                    # state and everything below (final ckpt save,
                    # final_step) uses it
                    step_times, timing_meta, state = time_fn_chained(
                        timed_step, state, warmup=1, iterations=iters,
                        chunk_size=min(5, iters), op_args=(batch, tgt),
                        compiler_options=comp_opts or None,
                    )
            else:
                step_times, timing_meta = [], {
                    "timing_mode": "chained",
                    "timing_method": "preempted before measurement",
                }

    if ckpt is not None:
        # forced final save — ON the preemption path this is the "final
        # save + flush" the SIGTERM contract promises (the restore after
        # preemption starts from the last finished step)
        ckpt.maybe_save(state, force=True)
        ckpt.close()

    if preempted_at is not None and not step_times:
        # preempted before any timed sample: there is nothing honest to
        # publish — save happened above; report the resume point instead
        # of a fabricated benchmark artifact
        result = {
            "preempted": True,
            "preempted_at_step": preempted_at,
            "mode": MODE_NAMES[stage],
            "zero_stage": stage,
            "resumed_from_step": resumed_from,
            "final_step": int(jax.device_get(state.step)),
            "checkpoint_saved": ckpt is not None,
            "losses": losses,
            "timestamp": time.time(),
        }
        if verbose:
            print(f"[train/{result['mode']}] preempted at step "
                  f"{preempted_at}; checkpoint "
                  f"{'saved' if ckpt is not None else 'DISABLED'} — "
                  "no benchmark artifact written")
        return result

    # Utilisation accounting (the train-side analogue of the E2E harness's
    # achieved-TFLOP/s; parity depth with reference ``run_mpi.py:217-225``):
    # backward ≈ 2x forward (grads w.r.t. weights + activations), plus the
    # per-param optimizer update.  Token count per optimizer step is the
    # full batch regardless of grad_accum/pipeline microbatching.
    tokens = inp["batch_size"] * inp["sequence_length"]
    n_params = int(sum(x.size for x in jax.tree.leaves(state.params)))
    fwd_flops = forward_flops(model_cfg, inp["batch_size"],
                              inp["sequence_length"])
    step_flops = 3 * fwd_flops + OPTIMIZER_FLOPS_PER_PARAM.get(
        opt_name, 18) * n_params
    # Device-work accounting under remat: full-policy remat re-runs each
    # block's forward during backward (+1 forward of matmul FLOPs); the
    # "dots" policy saves matmul outputs, so its recompute is elementwise
    # only — zero extra FLOPs under this matmul-only analytic count.
    # ``model_flops_per_step``/``achieved_tflops_per_second`` stay MODEL
    # flops (useful work per second, comparable across remat policies);
    # ``*_incl_recompute`` is the device-work rate.
    recompute_flops = (
        fwd_flops if (model_cfg.remat and model_cfg.remat_policy == "full")
        else 0
    )
    mean_step = float(np.mean(step_times))

    result = {
        "experiment": config.get("experiment", {}),
        "backend": "xla_tpu",
        "config": config,
        "mode": MODE_NAMES[stage],
        "zero_stage": stage,
        "resumed_from_step": resumed_from,
        # quantised gradient reduction (docs/compression.md): "none" =
        # the GSPMD all-reduce path; int8/fp8 = the error-feedback ring
        "grad_compression": grad_compression,
        "compression_accum_dtype": (
            comp_accum if grad_compression != "none" else None
        ),
        # graceful-preemption marker: True when SIGTERM cut the loop short
        # after >=1 timed sample (stats below cover the completed steps)
        "preempted": preempted_at is not None,
        "preempted_at_step": preempted_at,
        "mesh": plan.mesh_dict(),
        "learning_rate": lr,
        "optimizer": opt_name,
        "moments_dtype": moments_dtype(train_cfg),
        "schedule": sched_name,
        "gradient_accumulation": grad_accum,
        "pipeline_schedule": pipeline_schedule if plan.pp > 1 else None,
        "remat": model_cfg.remat,
        "remat_policy": model_cfg.remat_policy if model_cfg.remat else None,
        # TP collective-matmul schedule (off = GSPMD fused; ring/bidir =
        # overlapped decomposition, docs/overlap.md)
        "tp_overlap": model_cfg.tp_overlap,
        "compiler_options": comp_opts or None,
        "compile_time_s": compile_time,
        "step_time": summarize(step_times),
        "num_params": n_params,
        "tokens_per_second": tokens / mean_step,
        "model_flops_per_step": step_flops,
        "forward_flops": fwd_flops,
        "recompute_flops_per_step": recompute_flops,
        "recompute_note": (
            "achieved_tflops_per_second counts MODEL flops; with "
            "remat_policy=full the device additionally re-runs ~1 forward "
            "of matmuls per step (see *_incl_recompute)"
            if recompute_flops else None
        ),
        "achieved_tflops_per_second": step_flops / mean_step / 1e12,
        "achieved_tflops_per_second_incl_recompute": (
            (step_flops + recompute_flops) / mean_step / 1e12),
        **timing_meta,
        "losses": losses,
        "final_step": int(state.step),
        "system_info": collect_system_info(),
        "timestamp": time.time(),
    }
    if verbose:
        st = result["step_time"]
        print(
            f"[train/{result['mode']}] step mean {st['mean'] * 1e3:.2f} ms, "
            f"{result['tokens_per_second']:.0f} tok/s, "
            f"{result['achieved_tflops_per_second']:.2f} TFLOP/s, "
            f"loss {losses[0]:.4f} -> {losses[-1]:.4f}"
        )
    if output_dir is not None:
        name = config.get("experiment", {}).get("name", "experiment")
        save_json(result, Path(output_dir) / f"train_{result['mode']}_{name}.json")
    return result


def run_train_from_config(
    config_path: str,
    zero1: bool = False,
    zero_stage: Optional[int] = None,
    output_dir: Optional[str] = None,
    devices: Optional[Sequence] = None,
    tp_overlap: Optional[str] = None,
    grad_compression: Optional[str] = None,
) -> dict[str, Any]:
    """``tp_overlap`` overrides the config's ``model.tp_overlap`` (the
    ``--tp-overlap`` CLI flag), mirroring ``run_e2e_from_config``;
    ``grad_compression`` overrides ``training.grad_compression`` the same
    way (the ``--grad-compression`` flag)."""
    config = load_config(config_path)
    if tp_overlap is not None:
        config.setdefault("model", {})["tp_overlap"] = tp_overlap
    if grad_compression is not None:
        config.setdefault("training", {})["grad_compression"] = \
            grad_compression
    out = output_dir or config.get("experiment", {}).get("output_dir")
    return run_train(config, zero1=zero1, zero_stage=zero_stage,
                     devices=devices, output_dir=out)
