"""Optimizer + LR-schedule construction from the ``training:`` config.

The reference trains only with Adam at a fixed LR (DeepSpeed config at
``test/ccl.py:74-89``, ``test/ds_mpi_test.py:16-24``); a complete framework
needs the standard optimizer/schedule matrix, built here from optax:

optimizer: adam (default) | adamw | sgd | adafactor
schedule:  constant (default) | cosine | warmup_cosine

``adafactor`` is the TPU-idiomatic large-model choice: factored second
moments make optimizer state sublinear in parameter count (Adam's mu/nu
double a 13B model's memory; adafactor adds row+column statistics only),
which is what lets the full 13B train-step artifact fit the single host
that simulates the 8-device mesh (``scripts/publish_baselines.py``).
"""

from __future__ import annotations

from typing import Any

import optax

OPTIMIZERS = ("adam", "adamw", "sgd", "adafactor")
SCHEDULES = ("constant", "cosine", "warmup_cosine")
DEFAULT_OPTIMIZER = "adam"
DEFAULT_SCHEDULE = "constant"
DEFAULT_LR = 1e-3


def resolve_names(train_cfg: dict[str, Any]) -> tuple[str, str]:
    """(optimizer, schedule) names as build_optimizer resolves them — the
    single source of truth: build_optimizer/build_schedule read the names
    through this function, so metadata can never disagree with the built
    optimizer."""
    return (train_cfg.get("optimizer", DEFAULT_OPTIMIZER),
            train_cfg.get("schedule", DEFAULT_SCHEDULE))


def learning_rate(train_cfg: dict[str, Any]) -> float:
    """The configured (peak) learning rate."""
    return float(train_cfg.get("learning_rate", DEFAULT_LR))


def build_schedule(train_cfg: dict[str, Any]) -> optax.Schedule:
    lr = learning_rate(train_cfg)
    _, name = resolve_names(train_cfg)
    if name == "constant":
        return optax.constant_schedule(lr)
    if name == "cosine":
        decay_steps = int(train_cfg.get("decay_steps", 1000))
        return optax.cosine_decay_schedule(lr, decay_steps)
    if name == "warmup_cosine":
        warmup = int(train_cfg.get("warmup_steps", 100))
        decay_steps = int(train_cfg.get("decay_steps", 1000))
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=warmup,
            decay_steps=decay_steps,
        )
    raise ValueError(
        f"unknown training.schedule {name!r}; known: {SCHEDULES}"
    )


def build_optimizer(train_cfg: dict[str, Any]) -> optax.GradientTransformation:
    """Build the optax optimizer described by the ``training:`` section."""
    name, _ = resolve_names(train_cfg)
    schedule = build_schedule(train_cfg)
    if name == "adam":
        return optax.adam(schedule)
    if name == "adamw":
        wd = float(train_cfg.get("weight_decay", 0.01))
        return optax.adamw(schedule, weight_decay=wd)
    if name == "sgd":
        momentum = train_cfg.get("momentum", 0.9)
        return optax.sgd(schedule, momentum=momentum)
    if name == "adafactor":
        return optax.adafactor(learning_rate=schedule)
    raise ValueError(
        f"unknown training.optimizer {name!r}; known: {OPTIMIZERS}"
    )
