"""Optimizer + LR-schedule construction from the ``training:`` config.

The reference trains only with Adam at a fixed LR (DeepSpeed config at
``test/ccl.py:74-89``, ``test/ds_mpi_test.py:16-24``); a complete framework
needs the standard optimizer/schedule matrix, built here from optax:

optimizer: adam (default) | adamw | sgd | adafactor
schedule:  constant (default) | cosine | warmup_cosine

``adafactor`` is the TPU-idiomatic large-model choice: factored second
moments make optimizer state sublinear in parameter count (Adam's mu/nu
double a 13B model's memory; adafactor adds row+column statistics only),
which is what lets the full 13B train-step artifact fit the single host
that simulates the 8-device mesh (``scripts/publish_baselines.py``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

OPTIMIZERS = ("adam", "adamw", "sgd", "adafactor")
SCHEDULES = ("constant", "cosine", "warmup_cosine")
DEFAULT_OPTIMIZER = "adam"
DEFAULT_SCHEDULE = "constant"
DEFAULT_LR = 1e-3

# training.grad_compression: quantised gradient reduction with error
# feedback (docs/compression.md); "none" is the uncompressed GSPMD path
GRAD_COMPRESSIONS = ("none", "int8", "fp8")
COMPRESSION_ACCUM_DTYPES = ("float32", "bfloat16")


class GradCompressionState(NamedTuple):
    """Error-feedback residual for compressed gradient reduction.

    ``residual`` is ``[dp, total_params]`` — each data-parallel rank's
    local quantisation error (``comm/compression.py::quantization_error``),
    flattened over the whole parameter pytree.  It lives as an
    optimizer-state leaf so it is sharded like the gradients
    (``P("dp")`` — one row per rank, never replicated), checkpointed with
    the rest of the optimizer state, and stored in ``moments_dtype`` when
    one is configured (the memory-reduced-Adam convention)."""

    residual: Any


def resolve_grad_compression(train_cfg: dict[str, Any]) -> str:
    """The configured ``training.grad_compression`` mode, validated."""
    mode = str(train_cfg.get("grad_compression", "none"))
    if mode not in GRAD_COMPRESSIONS:
        raise ValueError(
            f"unknown training.grad_compression {mode!r}; known: "
            f"{GRAD_COMPRESSIONS}"
        )
    return mode


def compression_accum_dtype(train_cfg: dict[str, Any]) -> str:
    """The configured ``training.compression_accum_dtype`` (the ring's
    accumulation precision; fp32 default, bf16 the reduced variant)."""
    dt = str(train_cfg.get("compression_accum_dtype", "float32"))
    if dt not in COMPRESSION_ACCUM_DTYPES:
        raise ValueError(
            f"unknown training.compression_accum_dtype {dt!r} "
            f"(expected one of {COMPRESSION_ACCUM_DTYPES})"
        )
    return dt


def init_error_feedback(params: Any, dp_size: int, dtype=jnp.float32,
                        sharding: Any = None) -> GradCompressionState:
    """Zero residual for the whole (flattened) parameter pytree — one
    row per data-parallel rank.  With ``sharding`` (the residual's
    ``P("dp")`` NamedSharding) the zeros are created DIRECTLY sharded via
    a jitted out-sharding: materialising the replicated ``[dp, total]``
    buffer first would transiently cost dp x the flat parameter bytes on
    one device — exactly the spike that matters at the 13B scale the
    train loop otherwise avoids."""
    total = int(sum(p.size for p in jax.tree.leaves(params)))
    shape, dt = (dp_size, total), jnp.dtype(dtype)
    if sharding is not None:
        zeros = jax.jit(
            lambda: jnp.zeros(shape, dt), out_shardings=sharding
        )()
    else:
        zeros = jnp.zeros(shape, dt)
    return GradCompressionState(residual=zeros)


def resolve_names(train_cfg: dict[str, Any]) -> tuple[str, str]:
    """(optimizer, schedule) names as build_optimizer resolves them — the
    single source of truth: build_optimizer/build_schedule read the names
    through this function, so metadata can never disagree with the built
    optimizer."""
    return (train_cfg.get("optimizer", DEFAULT_OPTIMIZER),
            train_cfg.get("schedule", DEFAULT_SCHEDULE))


def learning_rate(train_cfg: dict[str, Any]) -> float:
    """The configured (peak) learning rate."""
    return float(train_cfg.get("learning_rate", DEFAULT_LR))


def build_schedule(train_cfg: dict[str, Any]) -> optax.Schedule:
    lr = learning_rate(train_cfg)
    _, name = resolve_names(train_cfg)
    if name == "constant":
        return optax.constant_schedule(lr)
    if name == "cosine":
        decay_steps = int(train_cfg.get("decay_steps", 1000))
        return optax.cosine_decay_schedule(lr, decay_steps)
    if name == "warmup_cosine":
        warmup = int(train_cfg.get("warmup_steps", 100))
        decay_steps = int(train_cfg.get("decay_steps", 1000))
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=warmup,
            decay_steps=decay_steps,
        )
    raise ValueError(
        f"unknown training.schedule {name!r}; known: {SCHEDULES}"
    )


def moments_dtype(train_cfg: dict[str, Any]) -> Optional[str]:
    """The configured optimizer-state storage dtype (None = optimizer
    default).  ``training.moments_dtype: bfloat16`` is the memory-reduced
    Adam the 16 GiB v5e chip needs at 1B/b8/s512: fp32 mu+nu are 8 bytes
    per parameter (9.7 GiB at 1.2B params — OOM next to params, grads and
    activations); bf16 moments halve that."""
    dt = train_cfg.get("moments_dtype")
    if dt is None:
        return None
    if dt not in ("bfloat16", "float16", "float32"):
        raise ValueError(
            f"unknown training.moments_dtype {dt!r} "
            "(expected bfloat16/float16/float32)"
        )
    return dt


def cast_moments(
    inner: optax.GradientTransformation, dtype
) -> optax.GradientTransformation:
    """Store ``inner``'s floating optimizer-state leaves in ``dtype``;
    the update math still runs in fp32 (state is upcast around
    ``inner.update``).  Generic over the wrapped transformation: every
    *wide* floating-point state leaf (Adam mu/nu, SGD momentum, adafactor
    statistics — fp16/bf16/fp32/fp64) is cast; integer leaves (step
    counts) and byte-wide quantised bookkeeping (int8 / fp8 residual
    caches from compressed-gradient state) pass through untouched —
    float-casting a quantised payload would corrupt it, and round-tripping
    it through fp32 in ``update`` would silently widen its storage."""
    dtype = jnp.dtype(dtype)

    def _castable(x) -> bool:
        if not hasattr(x, "dtype"):
            return False  # python scalars / exotic leaves: leave alone
        dt = jnp.dtype(x.dtype)
        # "wide float" = >= 2-byte IEEE float: excludes integers, bools,
        # AND the 1-byte fp8 wire dtypes used as quantised bookkeeping
        return jnp.issubdtype(dt, jnp.floating) and dt.itemsize >= 2

    def _cast(tree, to):
        return jax.tree.map(
            lambda x: x.astype(to) if _castable(x) else x, tree,
        )

    def init(params):
        return _cast(inner.init(params), dtype)

    def update(updates, state, params=None):
        updates, new_state = inner.update(
            updates, _cast(state, jnp.float32), params
        )
        return updates, _cast(new_state, dtype)

    return optax.GradientTransformation(init, update)


def build_optimizer(train_cfg: dict[str, Any]) -> optax.GradientTransformation:
    """Build the optax optimizer described by the ``training:`` section."""
    name, _ = resolve_names(train_cfg)
    schedule = build_schedule(train_cfg)
    if name == "adam":
        opt = optax.adam(schedule)
    elif name == "adamw":
        wd = float(train_cfg.get("weight_decay", 0.01))
        opt = optax.adamw(schedule, weight_decay=wd)
    elif name == "sgd":
        momentum = train_cfg.get("momentum", 0.9)
        opt = optax.sgd(schedule, momentum=momentum)
    elif name == "adafactor":
        opt = optax.adafactor(learning_rate=schedule)
    else:
        raise ValueError(
            f"unknown training.optimizer {name!r}; known: {OPTIMIZERS}"
        )
    mdt = moments_dtype(train_cfg)
    if mdt is not None:
        opt = cast_moments(opt, mdt)
    return opt
