"""Checkpoint / resume for the training loop (orbax-backed).

The reference has **no** checkpointing — sweep resume is manual, by virtue of
one-JSON-per-config outputs recomputed idempotently (SURVEY §5.4, reference
``collectives/1d/stats.py`` re-reads artifacts).  A real training framework
needs train-state checkpointing, so this subsystem goes beyond parity:

- ``CheckpointManager``-based save/restore of the full ``TrainState``
  (params + optimizer state + step counter), preserving shardings: restore
  takes an ``abstract_state`` built from the live sharded state, so orbax
  places every shard directly on its owning device — no host-side gather,
  which matters at 7B/13B scale where the replicated state would not fit
  one host.
- Retention policy (``max_to_keep``) and save interval, mirroring the
  knobs a DeepSpeed user would configure in ``ds_config`` (the reference's
  training entry point, ``test/ccl.py:74-89``, configures the engine but
  never saves).
- Multi-host safe: orbax coordinates the write across processes; under a
  single-process simulated mesh it degrades to a plain local save.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from dlbb_tpu.train.loop import TrainState

__all__ = [
    "CheckpointConfig",
    "Checkpointer",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
]


class CheckpointConfig:
    """Checkpoint policy knobs (YAML section ``training.checkpoint``)."""

    def __init__(
        self,
        directory: str,
        save_interval_steps: int = 1,
        max_to_keep: int = 3,
        enabled: bool = True,
    ) -> None:
        self.directory = str(Path(directory).absolute())
        self.save_interval_steps = int(save_interval_steps)
        self.max_to_keep = int(max_to_keep)
        self.enabled = bool(enabled)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CheckpointConfig":
        return cls(
            directory=d["directory"],
            save_interval_steps=d.get("save_interval_steps", 1),
            max_to_keep=d.get("max_to_keep", 3),
            enabled=d.get("enabled", True),
        )


class Checkpointer:
    """Thin lifecycle wrapper around ``ocp.CheckpointManager``.

    Usage::

        ckpt = Checkpointer(CheckpointConfig("/tmp/run1"))
        state = ckpt.restore_or(state)          # resume if a checkpoint exists
        for ...:
            state, loss = jit_step(state, batch, tgt)
            ckpt.maybe_save(state)
        ckpt.close()
    """

    def __init__(self, config: CheckpointConfig) -> None:
        self.config = config
        os.makedirs(config.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            config.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                save_interval_steps=config.save_interval_steps,
                enable_async_checkpointing=False,
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def maybe_save(self, state: TrainState, force: bool = False) -> bool:
        """Save if the manager's interval policy says so. Returns True if saved."""
        if not self.config.enabled:
            return False
        step = int(jax.device_get(state.step))
        if step in self._mgr.all_steps():
            return False  # already on disk (e.g. final force after interval save)
        return bool(
            self._mgr.save(
                step, args=ocp.args.StandardSave(_as_pytree(state)), force=force
            )
        )

    def restore(self, like: TrainState, step: Optional[int] = None) -> TrainState:
        """Restore at ``step`` (default: latest) with ``like``'s shardings."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.config.directory}"
            )
        abstract = jax.tree.map(_abstractify, _as_pytree(like))
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        return _from_pytree(restored)

    def restore_or(self, state: TrainState) -> TrainState:
        """Resume from the latest checkpoint if one exists, else pass through."""
        if self.latest_step() is None:
            return state
        return self.restore(state)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _as_pytree(state: TrainState) -> dict[str, Any]:
    # NamedTuple -> plain dict: orbax's Standard handlers round-trip dicts of
    # arrays; the TrainState wrapper is re-applied on restore.
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": state.step,
    }


def _from_pytree(tree: dict[str, Any]) -> TrainState:
    return TrainState(tree["params"], tree["opt_state"], tree["step"])


def _abstractify(x):
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


def save_checkpoint(directory: str, state: TrainState) -> None:
    """One-shot save (no manager lifecycle)."""
    with Checkpointer(CheckpointConfig(directory)) as ckpt:
        ckpt.maybe_save(state, force=True)


def restore_checkpoint(
    directory: str, like: TrainState, step: Optional[int] = None
) -> TrainState:
    """One-shot restore with ``like``'s shardings."""
    with Checkpointer(CheckpointConfig(directory)) as ckpt:
        return ckpt.restore(like, step=step)


def latest_step(directory: str) -> Optional[int]:
    if not Path(directory).exists():
        return None
    with Checkpointer(CheckpointConfig(directory)) as ckpt:
        return ckpt.latest_step()
