"""Checkpoint / resume for the training loop (orbax-backed).

The reference has **no** checkpointing — sweep resume is manual, by virtue of
one-JSON-per-config outputs recomputed idempotently (SURVEY §5.4, reference
``collectives/1d/stats.py`` re-reads artifacts).  A real training framework
needs train-state checkpointing, so this subsystem goes beyond parity:

- ``CheckpointManager``-based save/restore of the full ``TrainState``
  (params + optimizer state + step counter), preserving shardings: restore
  takes an ``abstract_state`` built from the live sharded state, so orbax
  places every shard directly on its owning device — no host-side gather,
  which matters at 7B/13B scale where the replicated state would not fit
  one host.
- Retention policy (``max_to_keep``) and save interval, mirroring the
  knobs a DeepSpeed user would configure in ``ds_config`` (the reference's
  training entry point, ``test/ccl.py:74-89``, configures the engine but
  never saves).
- Multi-host safe: orbax coordinates the write across processes; under a
  single-process simulated mesh it degrades to a plain local save.
- **Integrity contract** (docs/resilience.md): every save writes a
  checksum manifest (sha256 + size per file, atomic) under
  ``<dir>/.integrity/<step>.json``; :meth:`Checkpointer.restore` verifies
  before restoring and refuses a corrupt step
  (:class:`~dlbb_tpu.resilience.errors.CheckpointCorruption`);
  :meth:`Checkpointer.restore_or` instead falls back to the newest
  *intact* step, logging which step was rejected and why — a torn or
  bit-rotted checkpoint can roll training back, never crash it or
  silently feed it garbage.
"""

from __future__ import annotations

import hashlib
import os
from pathlib import Path
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.errors import CheckpointCorruption
from dlbb_tpu.train.loop import TrainState
from dlbb_tpu.utils.config import save_json

__all__ = [
    "CheckpointConfig",
    "Checkpointer",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
]

INTEGRITY_DIRNAME = ".integrity"
INTEGRITY_SCHEMA = "dlbb_ckpt_integrity_v1"


def _file_digest(path: Path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class CheckpointConfig:
    """Checkpoint policy knobs (YAML section ``training.checkpoint``)."""

    def __init__(
        self,
        directory: str,
        save_interval_steps: int = 1,
        max_to_keep: int = 3,
        enabled: bool = True,
        integrity: bool = True,
    ) -> None:
        self.directory = str(Path(directory).absolute())
        self.save_interval_steps = int(save_interval_steps)
        self.max_to_keep = int(max_to_keep)
        self.enabled = bool(enabled)
        # per-save checksum manifests (docs/resilience.md).  Each save
        # re-reads and sha256s the whole step tree — O(checkpoint bytes)
        # added to every interval save; at multi-GB state scale set
        # ``integrity: false`` to trade corruption detection for save
        # throughput (steps then restore as "unverified", like legacy
        # checkpoints)
        self.integrity = bool(integrity)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CheckpointConfig":
        return cls(
            directory=d["directory"],
            save_interval_steps=d.get("save_interval_steps", 1),
            max_to_keep=d.get("max_to_keep", 3),
            enabled=d.get("enabled", True),
            integrity=d.get("integrity", True),
        )


class Checkpointer:
    """Thin lifecycle wrapper around ``ocp.CheckpointManager``.

    Usage::

        ckpt = Checkpointer(CheckpointConfig("/tmp/run1"))
        state = ckpt.restore_or(state)          # resume if a checkpoint exists
        for ...:
            state, loss = jit_step(state, batch, tgt)
            ckpt.maybe_save(state)
        ckpt.close()
    """

    def __init__(self, config: CheckpointConfig) -> None:
        self.config = config
        os.makedirs(config.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            config.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                save_interval_steps=config.save_interval_steps,
                enable_async_checkpointing=False,
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    # ---- integrity manifest (docs/resilience.md) -----------------------

    def _integrity_dir(self) -> Path:
        return Path(self.config.directory) / INTEGRITY_DIRNAME

    def _manifest_path(self, step: int) -> Path:
        return self._integrity_dir() / f"{int(step)}.json"

    def _step_dir(self, step: int) -> Optional[Path]:
        """The on-disk directory of ``step`` (orbax's default layout is
        ``<dir>/<step>``; fall back to a scan so a customised
        ``step_name_format`` still verifies)."""
        base = Path(self.config.directory)
        cand = base / str(int(step))
        if cand.is_dir():
            return cand
        for p in sorted(base.iterdir()):
            if p.is_dir() and p.name != INTEGRITY_DIRNAME \
                    and p.name.lstrip("0") in (str(int(step)), "") \
                    and p.name.strip("0") != "":
                return p
            if p.is_dir() and p.name.endswith(f"_{int(step)}"):
                return p
        return None

    def _write_integrity(self, step: int) -> None:
        """Checksum every file of the just-saved step (sha256 + size),
        atomically; prune manifests of steps retention already deleted."""
        step_dir = self._step_dir(step)
        if step_dir is None:
            return
        files = {}
        for p in sorted(step_dir.rglob("*")):
            if p.is_file():
                files[str(p.relative_to(step_dir))] = {
                    "sha256": _file_digest(p),
                    "bytes": p.stat().st_size,
                }
        save_json(
            {"schema": INTEGRITY_SCHEMA, "step": int(step), "files": files},
            self._manifest_path(step),
        )
        live = {int(s) for s in self._mgr.all_steps()}
        for m in self._integrity_dir().glob("*.json"):
            try:
                if int(m.stem) not in live:
                    m.unlink()
            except ValueError:
                continue

    def verify_step(self, step: int) -> tuple[bool, str]:
        """Does ``step`` on disk match its integrity manifest?

        Returns ``(ok, reason)``.  A step saved before this subsystem
        existed has no manifest: accepted (``"unverified"``) so legacy
        checkpoints keep restoring, but every new save is covered."""
        import json

        step_dir = self._step_dir(step)
        if step_dir is None:
            return False, "step directory missing"
        mpath = self._manifest_path(step)
        if not mpath.exists():
            return True, "unverified (no integrity manifest; pre-PR5 save)"
        try:
            manifest = json.loads(mpath.read_text())
        except (OSError, json.JSONDecodeError) as e:
            return False, f"integrity manifest unreadable ({e})"
        for rel, meta in manifest.get("files", {}).items():
            p = step_dir / rel
            if not p.is_file():
                return False, f"missing file {rel}"
            if p.stat().st_size != meta["bytes"]:
                return False, (f"size mismatch on {rel} "
                               f"({p.stat().st_size} != {meta['bytes']})")
            if _file_digest(p) != meta["sha256"]:
                return False, f"checksum mismatch on {rel}"
        return True, "ok"

    def latest_intact_step(self) -> Optional[int]:
        """Newest step that passes :meth:`verify_step` (None if none)."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            if self.verify_step(int(step))[0]:
                return int(step)
        return None

    # ---- save / restore ------------------------------------------------

    def maybe_save(self, state: TrainState, force: bool = False) -> bool:
        """Save if the manager's interval policy says so. Returns True if
        saved.  Every save is followed by its integrity manifest."""
        if not self.config.enabled:
            return False
        step = int(jax.device_get(state.step))
        if step in self._mgr.all_steps():
            return False  # already on disk (e.g. final force after interval save)
        from dlbb_tpu.obs import spans

        with spans.span("checkpoint-save", cat="checkpoint", step=step,
                        forced=force):
            saved = bool(
                self._mgr.save(
                    step, args=ocp.args.StandardSave(_as_pytree(state)),
                    force=force
                )
            )
            if saved and self.config.integrity:
                # async checkpointing is disabled in __init__, so the wait
                # is a no-op today; it stays for correctness if that ever
                # flips (the manifest must hash the COMPLETED write)
                self._mgr.wait_until_finished()
                self._write_integrity(step)
                if inject.fire("ckpt-corrupt"):
                    # chaos harness: bit-rot the payload AFTER its
                    # manifest — verification must reject this step and
                    # restore_or must fall back to the newest intact one
                    self._corrupt_step(step)
        return saved

    def _corrupt_step(self, step: int) -> None:
        step_dir = self._step_dir(step)
        if step_dir is None:
            return
        victims = [p for p in sorted(step_dir.rglob("*"))
                   if p.is_file() and p.stat().st_size > 0]
        if not victims:
            return
        victim = max(victims, key=lambda p: p.stat().st_size)
        blob = bytearray(victim.read_bytes())
        mid = len(blob) // 2
        blob[mid] = blob[mid] ^ 0xFF
        victim.write_bytes(bytes(blob[: max(1, mid)]))  # flip + truncate

    def restore(self, like: TrainState, step: Optional[int] = None) -> TrainState:
        """Restore at ``step`` (default: latest) with ``like``'s shardings.

        Verifies integrity first and raises
        :class:`~dlbb_tpu.resilience.errors.CheckpointCorruption` on a
        corrupt step — an explicit restore must fail closed, not feed the
        trainer a torn state (``restore_or`` is the falling-back path)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.config.directory}"
            )
        ok, why = self.verify_step(int(step))
        if not ok:
            raise CheckpointCorruption(
                f"checkpoint step {step} under {self.config.directory} "
                f"failed integrity verification: {why}"
            )
        abstract = jax.tree.map(_abstractify, _as_pytree(like))
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        return _from_pytree(restored)

    def restore_or(self, state: TrainState) -> TrainState:
        """Resume from the newest INTACT checkpoint; pass through when none.

        Every candidate step is verified (and its restore attempted)
        newest-first; a corrupt or unrestorable step is logged — which
        step, and why — and the next older one is tried, so a torn final
        save after a crash rolls training back one interval instead of
        wedging the resume."""
        steps = sorted(self._mgr.all_steps(), reverse=True)
        for step in steps:
            ok, why = self.verify_step(int(step))
            if not ok:
                print(f"[checkpoint] step {step}: integrity FAILED ({why})"
                      " — falling back to the previous step")
                continue
            try:
                return self.restore(state, step=int(step))
            except CheckpointCorruption:
                raise  # verify_step already passed; a raise here is a bug
            except Exception as e:  # noqa: BLE001 — fall back, loudly
                print(f"[checkpoint] step {step}: restore failed "
                      f"({type(e).__name__}: {e}) — falling back to the "
                      "previous step")
                continue
        if steps:
            print(f"[checkpoint] no intact checkpoint among steps "
                  f"{steps} under {self.config.directory}; starting from "
                  "the initial state")
        return state

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _as_pytree(state: TrainState) -> dict[str, Any]:
    # NamedTuple -> plain dict: orbax's Standard handlers round-trip dicts of
    # arrays; the TrainState wrapper is re-applied on restore.
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": state.step,
    }


def _from_pytree(tree: dict[str, Any]) -> TrainState:
    return TrainState(tree["params"], tree["opt_state"], tree["step"])


def _abstractify(x):
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


def save_checkpoint(directory: str, state: TrainState) -> None:
    """One-shot save (no manager lifecycle)."""
    with Checkpointer(CheckpointConfig(directory)) as ckpt:
        ckpt.maybe_save(state, force=True)


def restore_checkpoint(
    directory: str, like: TrainState, step: Optional[int] = None
) -> TrainState:
    """One-shot restore with ``like``'s shardings."""
    with Checkpointer(CheckpointConfig(directory)) as ckpt:
        return ckpt.restore(like, step=step)


def latest_step(directory: str) -> Optional[int]:
    if not Path(directory).exists():
        return None
    with Checkpointer(CheckpointConfig(directory)) as ckpt:
        return ckpt.latest_step()
