"""Offline statistics pipeline (L6 replacement).

Reference-compatible schemas: 1D per-file ``*_stats.json`` + consolidated CSV
(``collectives/1d/stats.py``), 3D standard + transposed CSVs
(``collectives/3d/stats.py``).  Bit-compatible columns matter more than
elegance (SURVEY §7 step 3) — this is the judged artifact format.
"""

from dlbb_tpu.stats.compare import write_comparison
from dlbb_tpu.stats.variants_report import write_variants_report
from dlbb_tpu.stats.stats1d import (
    calculate_bandwidth,
    calculate_statistics,
    process_1d_results,
)
from dlbb_tpu.stats.stats3d import process_3d_results
from dlbb_tpu.stats.serving_report import write_serving_report

__all__ = [
    "calculate_statistics",
    "calculate_bandwidth",
    "process_1d_results",
    "process_3d_results",
    "write_comparison",
    "write_serving_report",
    "write_variants_report",
]
