"""Reference-vs-dlbb_tpu head-to-head comparison report.

Runs the repo's own stats pipeline over BOTH artifact corpora — the
reference's checked-in result JSONs (``/root/reference/collectives/{1d,3d}/
results/<backend>/``, its §6 published baseline) and this repo's
``results/{1d,3d}/`` — joins them per configuration, and emits one committed
CSV + markdown report stating, per (op x size x ranks) point, whether
``xla_tpu`` matches, beats, or loses to the BEST reference backend at that
point (best = lowest mean time across openmpi / intelmpi / dsgloo / dsccl
and, for 3D, every dsccl tuning variant directory).

Honesty caveats (carried into the report header):

- the reference corpus was measured on its 56-core CPU node with real
  MPI/oneCCL processes; this repo's committed corpus is the CPU-*simulated*
  8-device mesh on this image's single core (XLA collectives over host RAM,
  not ICI — there is no multi-chip TPU here to measure).  The comparison is
  therefore stack-vs-stack at equal rank counts, not fabric-vs-fabric.
- chunked-timing rows (``timing_granularity`` column) aggregate chunk
  means; mean comparisons remain valid, tail comparisons do not.
- the reference publishes no E2E number (BASELINE.md); the E2E section
  compares against the re-measured reference-stack torch-CPU baseline
  (``bench_baseline_cpu.json``) and reports the TPU-chip numbers from
  ``BENCH_r*.json``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import numpy as np

from dlbb_tpu.stats.stats1d import process_file as process_1d_file
from dlbb_tpu.stats.stats3d import calculate_statistics_3d

# Above/below these speedup thresholds the verdict is beat/lose; between
# them the difference is within run-to-run noise and counts as a match.
BEAT, LOSE = 1.05, 0.95

# Rows whose own-side artifact was measured on the CPU-simulated mesh
# (system_info.backend == "cpu") are environment-vs-environment, not
# stack-vs-stack: 8-56 virtual devices serialised on one host core against
# the reference's real 56-core MPI node.  They get this verdict CLASS
# (structurally, not as prose caveat); the raw numbers and the speedup-based
# ``raw_verdict`` are kept alongside.
NOT_COMPARABLE = "not_comparable(simulated)"

COLUMNS_1D = [
    "operation", "data_size_name", "num_ranks", "xla_dtype",
    "ref_best_backend", "ref_best_mean_us", "ref_best_bandwidth_gbps",
    "xla_mean_us", "xla_bandwidth_gbps",
    # analytic per-device wire bytes of the own-side implementation
    # (stats1d carries it per row): bandwidth columns normalise by
    # LOGICAL payload, so this is where a compressed row's wire saving
    # is visible next to its uncompressed baseline (docs/compression.md)
    "xla_bytes_on_wire",
    "speedup", "verdict",
    "raw_verdict",
]

COLUMNS_3D = [
    "operation", "num_ranks", "batch", "seq_len", "hidden_dim",
    "tensor_size_mb", "ref_best_backend", "ref_best_mean_ms",
    "xla_mean_ms", "speedup", "verdict", "raw_verdict",
]


def _raw_verdict(speedup: float) -> str:
    if speedup >= BEAT:
        return "beat"
    if speedup <= LOSE:
        return "lose"
    return "match"


def _verdict_pair(speedup: float, own_backend: Optional[str]) -> dict:
    """verdict (class-aware) + raw_verdict (speedup-only) columns."""
    raw = _raw_verdict(speedup)
    verdict = NOT_COMPARABLE if own_backend == "cpu" else raw
    return {"verdict": verdict, "raw_verdict": raw}


def _rows_1d(results_dir: Path) -> list[dict[str, Any]]:
    """Stats rows for every 1D result JSON in one directory (in memory —
    same math as ``process_1d_results``, no artifacts written)."""
    rows = []
    for f in sorted(Path(results_dir).glob("*.json")):
        if f.name.endswith("_stats.json"):
            continue
        try:
            rows.append(process_1d_file(f))
        except Exception:  # noqa: BLE001 — per-file resilience
            continue
    return rows


def _rows_3d(results_dir: Path, backend: str) -> list[dict[str, Any]]:
    rows = []
    for f in sorted(Path(results_dir).glob("*.json")):
        if f.name.endswith("_stats.json"):
            continue
        try:
            data = json.loads(f.read_text())
            shape = data["tensor_shape"]
            rows.append({
                "backend": backend,
                "measured_backend": (data.get("system_info") or {}).get(
                    "backend"),
                "operation": data["operation"],
                "num_ranks": data["num_ranks"],
                "batch": shape["batch"],
                "seq_len": shape["seq_len"],
                "hidden_dim": shape["hidden_dim"],
                "tensor_size_mb": data["tensor_size_mb"],
                **calculate_statistics_3d(data["timings"]),
            })
        except Exception:  # noqa: BLE001
            continue
    return rows


def compare_1d(
    ref_results_root: Path, own_results_dir: Path
) -> list[dict[str, Any]]:
    """Join per (operation, data_size_name, num_ranks); one output row per
    config both corpora cover."""
    own = _rows_1d(own_results_dir)
    if not own or not Path(ref_results_root).is_dir():
        return []
    ref_best: dict[tuple, dict] = {}
    for backend_dir in sorted(Path(ref_results_root).iterdir()):
        if not backend_dir.is_dir():
            continue
        for r in _rows_1d(backend_dir):
            key = (r["operation"], r["data_size_name"], r["num_ranks"])
            if (key not in ref_best
                    or r["mean_time_us"] < ref_best[key]["mean_time_us"]):
                ref_best[key] = dict(r, backend=backend_dir.name)

    out = []
    for r in own:
        # own-side rows are keyed by (op, size, ranks, dtype): the corpus
        # carries bf16 (TPU-native) + fp32 (north-star companion) + fp16
        # (the reference's own dtype — parity slice), each joined against
        # the same reference best
        key = (r["operation"], r["data_size_name"], r["num_ranks"])
        ref = ref_best.get(key)
        if ref is None:
            continue
        speedup = ref["mean_time_us"] / r["mean_time_us"]
        out.append({
            "operation": key[0],
            "data_size_name": key[1],
            "num_ranks": key[2],
            "xla_dtype": r.get("dtype", ""),
            "ref_best_backend": ref["backend"],
            "ref_best_mean_us": round(ref["mean_time_us"], 3),
            "ref_best_bandwidth_gbps": (
                round(ref["bandwidth_gbps"], 4)
                if ref["bandwidth_gbps"] is not None else None
            ),
            "xla_mean_us": round(r["mean_time_us"], 3),
            "xla_bandwidth_gbps": (
                round(r["bandwidth_gbps"], 4)
                if r["bandwidth_gbps"] is not None else None
            ),
            "xla_bytes_on_wire": r.get("bytes_on_wire"),
            "speedup": round(speedup, 4),
            **_verdict_pair(speedup, r.get("backend")),
        })
    out.sort(key=lambda r: (r["operation"], r["num_ranks"],
                            r["xla_dtype"], r["xla_mean_us"]))
    return out


def compare_3d(
    ref_results_root: Path, own_results_dir: Path
) -> list[dict[str, Any]]:
    """Join per (operation, ranks, batch, seq, hidden).  Every reference
    directory — the four backends AND the dsccl tuning variants — competes
    for "best", because the tuned runs are legitimately the reference's
    best published numbers (SURVEY §2.3)."""
    own = _rows_3d(own_results_dir, "xla_tpu")
    if not own or not Path(ref_results_root).is_dir():
        return []
    ref_best: dict[tuple, dict] = {}
    for backend_dir in sorted(Path(ref_results_root).iterdir()):
        if not backend_dir.is_dir():
            continue
        for r in _rows_3d(backend_dir, backend_dir.name):
            key = (r["operation"], r["num_ranks"], r["batch"],
                   r["seq_len"], r["hidden_dim"])
            if (key not in ref_best
                    or r["mean_time_ms"] < ref_best[key]["mean_time_ms"]):
                ref_best[key] = r

    out = []
    for r in own:
        key = (r["operation"], r["num_ranks"], r["batch"],
               r["seq_len"], r["hidden_dim"])
        ref = ref_best.get(key)
        if ref is None:
            continue
        speedup = ref["mean_time_ms"] / r["mean_time_ms"]
        out.append({
            "operation": key[0], "num_ranks": key[1], "batch": key[2],
            "seq_len": key[3], "hidden_dim": key[4],
            "tensor_size_mb": r["tensor_size_mb"],
            "ref_best_backend": ref["backend"],
            "ref_best_mean_ms": round(ref["mean_time_ms"], 4),
            "xla_mean_ms": round(r["mean_time_ms"], 4),
            "speedup": round(speedup, 4),
            **_verdict_pair(speedup, r.get("measured_backend")),
        })
    out.sort(key=lambda r: (r["operation"], r["num_ranks"],
                            r["hidden_dim"], r["seq_len"], r["batch"]))
    return out


def _e2e_rows(repo_root: Path) -> list[dict[str, Any]]:
    """E2E tokens/s vs the reference-stack CPU baseline, from the committed
    bench artifacts (TPU-chip numbers, not the simulated mesh), plus the
    per-config real-chip e2e corpus under ``results/e2e`` (attention-mode
    ladder, long-context ladder, infeasibility boundaries)."""
    rows = []
    cpu = repo_root / "bench_baseline_cpu.json"
    base_tps = (json.loads(cpu.read_text())["tokens_per_second"]
                if cpu.exists() else None)
    e2e_dir = repo_root / "results" / "e2e"
    if e2e_dir.exists():
        # dedupe by experiment name: if a measured artifact and a stale
        # *_infeasible.json coexist transiently (cleanup happens only on
        # publisher success), the measured one wins — mirrors
        # stage_baseline's setdefault logic
        by_name: dict[str, dict] = {}
        for f in sorted(e2e_dir.glob("*.json")):
            try:
                r = json.loads(f.read_text())
            except Exception:  # noqa: BLE001
                continue
            name = r.get("experiment", {}).get("name", f.stem)
            prev = by_name.get(name)
            if prev is not None:
                prev_measured = prev.get("status") != "infeasible"
                this_measured = r.get("status") != "infeasible"
                if prev_measured or not this_measured:
                    continue
            by_name[name] = r
        for name, r in by_name.items():
            sysinfo = r.get("system_info") or {}
            device = (
                f"{sysinfo.get('device_kind', '?')} x "
                f"{sysinfo.get('num_devices', '?')}"
            )
            simulated = sysinfo.get("backend") == "cpu"
            if r.get("status") == "infeasible":
                rows.append({
                    "config": f"{name} (results/e2e)",
                    "device": (device if sysinfo else "(not recorded)"),
                    "reference_cpu_stack_tokens_per_s": None,
                    "xla_tpu_tokens_per_s": None,
                    "speedup": None,
                    "verdict": "infeasible (see artifact reason)",
                })
                continue
            if "tokens_per_second" not in r:
                continue
            tps = r["tokens_per_second"]
            # the CPU-stack baseline was measured at the reference's
            # b8/s512 1B shape — speedup only claimed at that shape,
            # and never for simulated-mesh artifacts
            comparable = (base_tps is not None and not simulated
                          and name.startswith("1b_")
                          and name.endswith("_s512_world1"))
            rows.append({
                "config": f"{name} (results/e2e)",
                "device": device + (" (simulated)" if simulated else ""),
                "reference_cpu_stack_tokens_per_s": (
                    round(base_tps, 1) if comparable else None),
                "xla_tpu_tokens_per_s": round(tps, 1),
                "speedup": (round(tps / base_tps, 2) if comparable
                            else None),
                "verdict": (
                    _raw_verdict(tps / base_tps) if comparable
                    else "(simulated mesh — sharding evidence, not a "
                         "chip number)" if simulated
                    else "(no reference number)"
                ),
            })
    if base_tps is None:
        return rows
    for bench_file in sorted(repo_root.glob("BENCH_r*.json")):
        try:
            b = json.loads(bench_file.read_text())
        except Exception:  # noqa: BLE001
            continue
        # driver BENCH records nest the bench.py line under "parsed"
        b = b.get("parsed", b)
        if "tokens/s" not in b.get("unit", ""):
            continue
        rows.append({
            "config": f"1B/simplified ({bench_file.name})",
            "device": "v5e chip",
            "reference_cpu_stack_tokens_per_s": round(base_tps, 1),
            "xla_tpu_tokens_per_s": b["value"],
            "speedup": round(b["value"] / base_tps, 2),
            "verdict": _raw_verdict(b["value"] / base_tps),
        })
        for name, extra in b.get("extras", {}).items():
            rows.append({
                "config": f"{name} ({bench_file.name})",
                "device": "v5e chip",
                "reference_cpu_stack_tokens_per_s": None,
                "xla_tpu_tokens_per_s": extra["tokens_per_second"],
                "speedup": None,
                "verdict": "(no reference number)",
            })
    return rows


def _counts(rows: list[dict]) -> dict[str, Any]:
    """beat/match/lose count only COMPARABLE rows (same-environment
    measurements); simulated rows are counted (and sub-broken-down by
    raw_verdict) under ``not_comparable_simulated``."""
    c: dict[str, Any] = {"beat": 0, "match": 0, "lose": 0,
                         "not_comparable_simulated": 0}
    raw = {"beat": 0, "match": 0, "lose": 0}
    for r in rows:
        if r["verdict"] == NOT_COMPARABLE:
            c["not_comparable_simulated"] += 1
            raw[r["raw_verdict"]] += 1
        elif r["verdict"] in c:
            c[r["verdict"]] += 1
    if c["not_comparable_simulated"]:
        c["not_comparable_raw_verdicts"] = raw
    return c


def md_table(rows: list[dict], columns: list[str]) -> list[str]:
    """Markdown table lines (None cells render blank) — the one table
    emitter shared by every stats report module."""
    return _md_table(rows, columns)


def _md_table(rows: list[dict], columns: list[str]) -> list[str]:
    lines = ["| " + " | ".join(columns) + " |",
             "|" + "---|" * len(columns)]
    for r in rows:
        lines.append(
            "| "
            + " | ".join(
                "" if r.get(c) is None else str(r[c]) for c in columns
            )
            + " |"
        )
    return lines


def _write_csv(rows: list[dict], columns: list[str], path: Path) -> None:
    import csv
    import io

    from dlbb_tpu.utils.config import atomic_write_text

    buf = io.StringIO()
    w = csv.DictWriter(buf, fieldnames=columns)
    w.writeheader()
    for r in rows:
        w.writerow({k: r.get(k) for k in columns})
    atomic_write_text(buf.getvalue(), path, newline="")


def _distinct_configs(rows: list[dict]) -> int:
    """Distinct reference configs covered — dtype is an own-side axis, so
    a (op, size, ranks) point measured in several dtypes (bf16/fp16/fp32)
    is ONE config with one row per dtype."""
    keys = set()
    for r in rows:
        if "data_size_name" in r:
            keys.add((r["operation"], r["data_size_name"], r["num_ranks"]))
        else:
            keys.add((r["operation"], r["num_ranks"], r["batch"],
                      r["seq_len"], r["hidden_dim"]))
    return len(keys)


def _summary_line(dim: str, rows: list[dict], c: dict) -> str:
    line = (f"- **{dim}** ({_distinct_configs(rows)} configs, "
            f"{len(rows)} rows): {c['beat']} beat, "
            f"{c['match']} match, {c['lose']} lose")
    if c["not_comparable_simulated"]:
        raw = c["not_comparable_raw_verdicts"]
        line += (f", {c['not_comparable_simulated']} not_comparable"
                 f"(simulated) [raw: {raw['beat']} beat / {raw['match']} "
                 f"match / {raw['lose']} lose]")
    return line


def write_comparison(
    ref_root: Path,
    own_1d: Path,
    own_3d: Path,
    out_dir: Path,
    repo_root: Optional[Path] = None,
) -> dict[str, Any]:
    """Produce ``comparison_{1d,3d}.csv`` + ``COMPARISON.md`` in
    ``out_dir``; returns the summary dict (also saved as JSON)."""
    ref_root = Path(ref_root)
    out_dir = Path(out_dir)
    rows_1d = compare_1d(ref_root / "collectives" / "1d" / "results", own_1d)
    rows_3d = compare_3d(ref_root / "collectives" / "3d" / "results", own_3d)
    e2e = _e2e_rows(repo_root) if repo_root else []

    _write_csv(rows_1d, COLUMNS_1D, out_dir / "comparison_1d.csv")
    _write_csv(rows_3d, COLUMNS_3D, out_dir / "comparison_3d.csv")

    c1, c3 = _counts(rows_1d), _counts(rows_3d)
    summary = {
        "1d": {"configs": _distinct_configs(rows_1d),
               "rows": len(rows_1d), **c1},
        "3d": {"configs": _distinct_configs(rows_3d),
               "rows": len(rows_3d), **c3},
        "e2e": e2e,
        "thresholds": {"beat": BEAT, "lose": LOSE},
    }

    md = [
        "# Reference vs dlbb_tpu — head-to-head comparison",
        "",
        "Per-config join of the reference's checked-in baseline corpus "
        "(`/root/reference/collectives/{1d,3d}/results/`) against this "
        "repo's committed `results/{1d,3d}/` corpus, both processed by "
        "this repo's stats pipeline.  `ref_best_*` is the fastest "
        "reference backend (incl. dsccl tuning variants) at that config; "
        "`speedup` = ref_best_mean / xla_mean (>1 = xla_tpu faster); "
        f"verdict thresholds: beat >= {BEAT}x, lose <= {LOSE}x.",
        "",
        "**Caveats** (see `dlbb_tpu/stats/compare.py` docstring): the "
        "reference corpus ran real MPI/oneCCL ranks on a 56-core node; "
        "this repo's corpus runs the CPU-simulated 8-device mesh on this "
        "image's single core (host-RAM collectives, not ICI).  The join "
        "covers the rank counts both corpora measured.  `xla_dtype` "
        "float16 rows use the reference's own payload dtype (the closest "
        "apples-to-apples rows); bf16 is the TPU-native dtype and fp32 "
        "the north-star companion.  The three dtypes share per-config "
        "*element counts* with the reference labels: fp16/bf16 rows "
        "therefore byte-match the fp16-measured reference, while fp32 "
        "rows move 2x the reference's bytes at the same size label "
        "(4 B/element) — their speedup/raw_verdict values compare "
        "doubled payload volume.  E2E "
        "rows are real-TPU-chip numbers vs the re-measured "
        "reference-stack torch-CPU baseline.",
        "",
        "## Summary",
        "",
        "beat/match/lose count comparable (same-environment) rows only; "
        "rows measured on the CPU-simulated mesh carry the structural "
        "verdict `not_comparable(simulated)` (raw numbers and the "
        "speedup-only `raw_verdict` kept per row).",
        "",
        _summary_line("1D", rows_1d, c1),
        _summary_line("3D", rows_3d, c3),
        "",
    ]
    if e2e:
        md += ["## E2E forward throughput "
               "(per-row device column; BENCH rows are the v5e chip)", ""]
        md += _md_table(
            e2e,
            ["config", "device", "reference_cpu_stack_tokens_per_s",
             "xla_tpu_tokens_per_s", "speedup", "verdict"],
        )
        md.append("")
    md += ["## 1D collectives (full table)", ""]
    md += _md_table(rows_1d, COLUMNS_1D)
    md += ["", "## 3D collectives (per op x ranks aggregate; "
           "full detail in comparison_3d.csv)", ""]
    agg_rows = []
    for (op, ranks) in sorted({(r["operation"], r["num_ranks"])
                               for r in rows_3d}):
        sub = [r for r in rows_3d
               if r["operation"] == op and r["num_ranks"] == ranks]
        cs = _counts(sub)
        agg_rows.append({
            "operation": op, "num_ranks": ranks, "configs": len(sub),
            "beat": cs["beat"], "match": cs["match"], "lose": cs["lose"],
            "not_comparable": cs["not_comparable_simulated"],
            "median_speedup": round(
                float(np.median([r["speedup"] for r in sub])), 3),
        })
    md += _md_table(agg_rows, ["operation", "num_ranks", "configs", "beat",
                               "match", "lose", "not_comparable",
                               "median_speedup"])
    md.append("")

    from dlbb_tpu.utils.config import atomic_write_text

    out_dir.mkdir(parents=True, exist_ok=True)
    (out_dir / "COMPARISON.md").write_text("\n".join(md))
    atomic_write_text(json.dumps(summary, indent=2) + "\n",
                      out_dir / "comparison_summary.json")
    return summary
