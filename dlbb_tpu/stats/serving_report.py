"""Serving-level report: consolidate ``serving_*.json`` results into a
CSV + markdown table (``SERVING.md``) — the serving analogue of the
variants/parallelism reports.  Pure file processing, no backend."""

from __future__ import annotations

import csv
import io
import json
from pathlib import Path
from typing import Any, Optional

from dlbb_tpu.utils.config import atomic_write_text

CSV_COLUMNS = (
    "name", "trace", "requests", "completed", "rejected", "mesh",
    "max_batch", "block_size", "max_seq",
    "goodput_tok_s", "throughput_tok_s",
    "ttft_p50_ms", "ttft_p99_ms", "ttft_p999_ms",
    "per_token_p50_ms", "per_token_p99_ms", "per_token_p999_ms",
    "peak_queue_depth", "peak_blocks_in_use", "decode_steps",
    "wall_seconds",
)


def _ms(summary: dict[str, Any], key: str) -> Optional[float]:
    v = summary.get(key)
    return None if v is None else round(float(v) * 1e3, 3)


def serving_row(report: dict[str, Any], name: str) -> dict[str, Any]:
    """One CSV/markdown row from a serving report JSON."""
    req = report.get("requests", {})
    ttft = report.get("ttft", {})
    ptl = report.get("per_token_latency", {})
    cache = report.get("cache", {})
    mesh = report.get("mesh", {})
    series = report.get("timeseries", {})
    serving = report.get("serving", {})
    return {
        "name": name,
        "trace": report.get("trace", {}).get("kind"),
        "requests": report.get("trace", {}).get("num_requests"),
        "completed": req.get("completed"),
        "rejected": req.get("rejected"),
        "mesh": "x".join(f"{k}{v}" for k, v in sorted(mesh.items())
                         if isinstance(v, int) and v > 1) or "1",
        "max_batch": serving.get("max_batch"),
        "block_size": serving.get("block_size"),
        "max_seq": serving.get("max_seq"),
        "goodput_tok_s": round(report.get("goodput_tokens_per_s", 0.0), 1),
        "throughput_tok_s": round(
            report.get("throughput_tokens_per_s", 0.0), 1),
        "ttft_p50_ms": _ms(ttft, "median"),
        "ttft_p99_ms": _ms(ttft, "p99"),
        "ttft_p999_ms": _ms(ttft, "p999"),
        "per_token_p50_ms": _ms(ptl, "median"),
        "per_token_p99_ms": _ms(ptl, "p99"),
        "per_token_p999_ms": _ms(ptl, "p999"),
        "peak_queue_depth": max(series.get("queue_depth", [0]) or [0]),
        "peak_blocks_in_use": cache.get("peak_blocks_in_use"),
        "decode_steps": report.get("decode_steps"),
        "wall_seconds": round(report.get("wall_seconds", 0.0), 3),
    }


def write_serving_report(results_dir: "str | Path",
                         output_dir: "str | Path") -> list[dict[str, Any]]:
    """Consolidate every ``serving_*.json`` under ``results_dir`` into
    ``output_dir``'s ``serving.csv`` + ``SERVING.md``.  Returns the rows
    (empty when there is nothing to report — callers skip, never clobber
    a committed report with an empty table)."""
    results_dir = Path(results_dir)
    rows = []
    for path in sorted(results_dir.rglob("serving_*.json")):
        if path.name == "serving_manifest.json":
            continue
        try:
            report = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if report.get("schema", "").startswith("dlbb_serving_report"):
            rows.append(serving_row(report, path.stem[len("serving_"):]))
    if not rows:
        return rows
    out = Path(output_dir)
    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=CSV_COLUMNS)
    writer.writeheader()
    writer.writerows(rows)
    atomic_write_text(buf.getvalue(), out / "serving.csv", newline="")

    lines = [
        "# Serving benchmark report",
        "",
        "Trace-driven continuous-batching runs "
        "(`python -m dlbb_tpu.cli serve`, docs/serving.md).  Goodput is "
        "completed-request output tokens per second; TTFT is "
        "arrival-to-first-token (queueing included); per-token latency "
        "is the decode-step interval each resident request observed.",
        "",
        "| run | trace | req | done | rej | mesh | goodput tok/s | "
        "TTFT p50/p99/p99.9 ms | tok p50/p99/p99.9 ms | peak queue | "
        "peak blocks |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['name']} | {r['trace']} | {r['requests']} | "
            f"{r['completed']} | {r['rejected']} | {r['mesh']} | "
            f"{r['goodput_tok_s']} | "
            f"{r['ttft_p50_ms']}/{r['ttft_p99_ms']}/{r['ttft_p999_ms']} | "
            f"{r['per_token_p50_ms']}/{r['per_token_p99_ms']}/"
            f"{r['per_token_p999_ms']} | "
            f"{r['peak_queue_depth']} | {r['peak_blocks_in_use']} |"
        )
    lines.append("")
    atomic_write_text("\n".join(lines), out / "SERVING.md")
    return rows
