"""Serving-level report: consolidate ``serving_*.json`` results into a
CSV + markdown table (``SERVING.md``) — the serving analogue of the
variants/parallelism reports.  Pure file processing, no backend."""

from __future__ import annotations

import csv
import io
import json
from pathlib import Path
from typing import Any, Optional

from dlbb_tpu.utils.config import atomic_write_text

CSV_COLUMNS = (
    "name", "trace", "requests", "completed", "rejected", "failed",
    "shed_rate", "deadline_shed", "past_deadline",
    "rej_queue_wait_ms", "mesh",
    "max_batch", "block_size", "max_seq",
    "goodput_tok_s", "throughput_tok_s",
    "ttft_p50_ms", "ttft_p99_ms", "ttft_p999_ms",
    "per_token_p50_ms", "per_token_p99_ms", "per_token_p999_ms",
    "peak_queue_depth", "peak_blocks_in_use", "decode_steps",
    "fused_steps", "prefill_chunks", "retries",
    "speculation", "spec_gamma", "acceptance_rate", "mean_accepted_len",
    "draft_overhead_s",
    "kv_quant", "prefix_hit_rate", "prefix_tokens_reused",
    "prefix_cow_blocks",
    "replicas", "failovers", "failover_penalty_ms",
    "hedges_issued", "hedges_won", "degrade_level",
    "wall_seconds",
)


def _ms(summary: dict[str, Any], key: str) -> Optional[float]:
    v = summary.get(key)
    return None if v is None else round(float(v) * 1e3, 3)


def _rejection_stats(req: dict[str, Any]) -> tuple[Optional[float],
                                                   Optional[float]]:
    """(shed_rate, mean queue-head wait at rejection in ms) — the
    admission-tuning signals.  ``rejected_detail`` is absent from
    pre-fast-path reports; both then fall back gracefully (shed rate
    from the counters, wait to None)."""
    arrived = req.get("arrived")
    rejected = req.get("rejected")
    shed = req.get("shed_rate")
    if shed is None and arrived:
        shed = (rejected or 0) / arrived
    detail = req.get("rejected_detail")
    wait_ms = None
    if detail:
        waits = [d["queue_wait_s"] for d in detail
                 if d.get("reason") == "queue-full"
                 and d.get("queue_wait_s") is not None]
        if waits:
            wait_ms = round(sum(waits) / len(waits) * 1e3, 3)
    return (None if shed is None else round(shed, 4)), wait_ms


def serving_row(report: dict[str, Any], name: str) -> dict[str, Any]:
    """One CSV/markdown row from a serving report JSON."""
    req = report.get("requests", {})
    ttft = report.get("ttft", {})
    ptl = report.get("per_token_latency", {})
    cache = report.get("cache", {})
    mesh = report.get("mesh", {})
    series = report.get("timeseries", {})
    serving = report.get("serving", {})
    fast = report.get("fast_path", {})
    spec = report.get("speculation", {})
    pre = report.get("prefix", {})
    shed_rate, rej_wait_ms = _rejection_stats(req)
    acc = spec.get("acceptance_rate")
    mal = spec.get("mean_accepted_len")
    draft_s = spec.get("draft_overhead_s")
    hit_rate = pre.get("hit_rate")
    return {
        "name": name,
        "trace": report.get("trace", {}).get("kind"),
        "requests": report.get("trace", {}).get("num_requests"),
        "completed": req.get("completed"),
        "rejected": req.get("rejected"),
        "failed": req.get("failed"),
        "shed_rate": shed_rate,
        "deadline_shed": req.get("deadline_shed"),
        "past_deadline": req.get("completed_past_deadline"),
        "retries": report.get("resilience", {}).get("retries"),
        "rej_queue_wait_ms": rej_wait_ms,
        "fused_steps": fast.get("fused_steps"),
        "prefill_chunks": fast.get("prefill_chunks"),
        "mesh": "x".join(f"{k}{v}" for k, v in sorted(mesh.items())
                         if isinstance(v, int) and v > 1) or "1",
        "max_batch": serving.get("max_batch"),
        "block_size": serving.get("block_size"),
        "max_seq": serving.get("max_seq"),
        "goodput_tok_s": round(report.get("goodput_tokens_per_s", 0.0), 1),
        "throughput_tok_s": round(
            report.get("throughput_tokens_per_s", 0.0), 1),
        "ttft_p50_ms": _ms(ttft, "median"),
        "ttft_p99_ms": _ms(ttft, "p99"),
        "ttft_p999_ms": _ms(ttft, "p999"),
        "per_token_p50_ms": _ms(ptl, "median"),
        "per_token_p99_ms": _ms(ptl, "p99"),
        "per_token_p999_ms": _ms(ptl, "p999"),
        "peak_queue_depth": max(series.get("queue_depth", [0]) or [0]),
        "peak_blocks_in_use": cache.get("peak_blocks_in_use"),
        "decode_steps": report.get("decode_steps"),
        # speculative decoding (docs/serving.md): absent from
        # pre-speculation reports and "off" runs — all None then
        "speculation": spec.get("mode"),
        "spec_gamma": spec.get("gamma"),
        "acceptance_rate": None if acc is None else round(acc, 4),
        "mean_accepted_len": None if mal is None else round(mal, 3),
        "draft_overhead_s": None if draft_s is None else round(draft_s, 4),
        # shared-prefix cache + quantized KV (docs/serving.md, "Prefix
        # cache & quantized KV"): absent from pre-prefix reports and
        # prefix-off runs — all None then
        "kv_quant": (pre.get("kv_quantization")
                     or serving.get("kv_quantization")),
        "prefix_hit_rate": (None if not pre.get("enabled") or
                            hit_rate is None else round(hit_rate, 4)),
        "prefix_tokens_reused": (pre.get("tokens_reused")
                                 if pre.get("enabled") else None),
        "prefix_cow_blocks": (pre.get("cow_blocks")
                              if pre.get("enabled") else None),
        # fleet-level robustness (docs/fleet.md): absent from
        # single-replica engine reports — all None then
        "replicas": (len(report["replicas"])
                     if report.get("replicas") else None),
        "failovers": report.get("failovers", {}).get("total"),
        "failover_penalty_ms": _ms(report, "failover_ttft_penalty_s"),
        "hedges_issued": report.get("hedges", {}).get("issued"),
        "hedges_won": report.get("hedges", {}).get("won"),
        "degrade_level": report.get("degrade", {}).get("name"),
        "wall_seconds": round(report.get("wall_seconds", 0.0), 3),
    }


def write_serving_report(results_dir: "str | Path",
                         output_dir: "str | Path") -> list[dict[str, Any]]:
    """Consolidate every ``serving_*.json`` under ``results_dir`` into
    ``output_dir``'s ``serving.csv`` + ``SERVING.md``.  Returns the rows
    (empty when there is nothing to report — callers skip, never clobber
    a committed report with an empty table)."""
    results_dir = Path(results_dir)
    rows = []
    paths = sorted(list(results_dir.rglob("serving_*.json"))
                   + list(results_dir.rglob("fleet_*.json")))
    for path in paths:
        if path.name == "serving_manifest.json":
            continue
        try:
            report = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        schema = report.get("schema", "")
        if schema.startswith(("dlbb_serving_report", "dlbb_fleet_report")):
            prefix = ("serving_" if path.name.startswith("serving_")
                      else "fleet_")
            rows.append(serving_row(report, path.stem[len(prefix):]))
    if not rows:
        return rows
    out = Path(output_dir)
    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=CSV_COLUMNS)
    writer.writeheader()
    writer.writerows(rows)
    atomic_write_text(buf.getvalue(), out / "serving.csv", newline="")

    lines = [
        "# Serving benchmark report",
        "",
        "Trace-driven continuous-batching runs "
        "(`python -m dlbb_tpu.cli serve`, docs/serving.md).  Goodput is "
        "completed-request output tokens per second; TTFT is "
        "arrival-to-first-token (queueing included); per-token latency "
        "is the decode-step interval each resident request observed.  "
        "Shed rate is queue-full rejections/arrived (infeasible "
        "rejections are a config/trace mismatch and excluded); "
        "\"rej wait\" is the mean time "
        "the queue HEAD had been waiting when an arrival was shed "
        "(high values = the queue bound is doing its job under real "
        "backlog; near-zero = capacity is set too low) — the "
        "admission-tuning signals (`requests.rejected_detail` carries "
        "the per-rejection reason + wait).  \"failed\" counts requests "
        "failed closed by the resilience layer (dispatch failure / "
        "hung dispatch, `docs/resilience.md`); \"late\" counts "
        "requests COMPLETED past their per-request SLO deadline and "
        "\"dl shed\" those shed from the queue because their deadline "
        "had already passed (distinct from queue-full shedding).  "
        "\"spec\" is the speculative-decoding drafter (with γ), "
        "\"acc\" the fraction of drafted tokens the target verify "
        "accepted, \"acc len\" the mean tokens committed per verify "
        "unit (accepted prefix + the verify's own bonus token), and "
        "\"draft s\" the host wall spent dispatching the draft model "
        "(docs/serving.md, \"Speculative decoding\").  \"kv\" is the "
        "KV-cache wire layout (int8 = quantized planes + fp32 scales), "
        "\"pfx hit\" the shared-prefix attach rate (prefix-cache hits / "
        "prefills) and \"pfx tok\" the prompt tokens whose prefill was "
        "skipped by attaching refcounted donor blocks (docs/serving.md, "
        "\"Prefix cache & quantized KV\").  Fleet rows "
        "(`fleet_*.json`, `cli serve --replicas N`, docs/fleet.md) add "
        "\"repl\" (failure domains; the mesh column is then ONE "
        "replica's mesh), \"failover\" (requests re-prefilled off a "
        "fenced replica, with the mean TTFT penalty vs clean requests "
        "in ms), \"hedge\" (duplicates won / issued) and \"degrade\" "
        "(the overload ladder's final level).",
        "",
        "| run | trace | req | done | rej | failed | shed | dl shed | "
        "late | rej wait ms | mesh | "
        "goodput tok/s | "
        "TTFT p50/p99/p99.9 ms | tok p50/p99/p99.9 ms | peak queue | "
        "peak blocks | spec | acc | acc len | draft s | kv | pfx hit | "
        "pfx tok | repl | failover (pen ms) | hedge | degrade |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|"
        "---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        shed = ("-" if r["shed_rate"] is None
                else f"{r['shed_rate'] * 100:.0f}%")
        wait = ("-" if r["rej_queue_wait_ms"] is None
                else r["rej_queue_wait_ms"])
        failed = "-" if r["failed"] is None else r["failed"]
        dl_shed = "-" if r["deadline_shed"] is None else r["deadline_shed"]
        late = "-" if r["past_deadline"] is None else r["past_deadline"]
        spec = ("-" if not r["speculation"] or r["speculation"] == "off"
                else (r["speculation"]
                      + (f" γ{r['spec_gamma']}" if r["spec_gamma"] else "")))
        acc = ("-" if r["acceptance_rate"] is None
               else f"{r['acceptance_rate']:.2f}")
        mal = ("-" if r["mean_accepted_len"] is None
               else f"{r['mean_accepted_len']:.2f}")
        draft_s = ("-" if r["draft_overhead_s"] is None
                   else f"{r['draft_overhead_s']:.3f}")
        kv = r["kv_quant"] or "-"
        pfx_hit = ("-" if r["prefix_hit_rate"] is None
                   else f"{r['prefix_hit_rate'] * 100:.0f}%")
        pfx_tok = ("-" if r["prefix_tokens_reused"] is None
                   else r["prefix_tokens_reused"])
        # fleet columns (docs/fleet.md): "-" on single-replica rows
        repl = "-" if r["replicas"] is None else r["replicas"]
        if r["failovers"] is None:
            fo = "-"
        elif r["failover_penalty_ms"] is not None:
            fo = f"{r['failovers']} ({r['failover_penalty_ms']:.1f})"
        else:
            fo = f"{r['failovers']}"
        hedge = ("-" if r["hedges_issued"] is None
                 else f"{r['hedges_won']}/{r['hedges_issued']}")
        degrade = r["degrade_level"] or "-"
        # per-token latency / cache peaks are engine-level; a fleet
        # row's aggregate view doesn't carry them
        ptl = ("-" if r["per_token_p50_ms"] is None else
               f"{r['per_token_p50_ms']}/{r['per_token_p99_ms']}/"
               f"{r['per_token_p999_ms']}")
        peak_blocks = ("-" if r["peak_blocks_in_use"] is None
                       else r["peak_blocks_in_use"])
        lines.append(
            f"| {r['name']} | {r['trace']} | {r['requests']} | "
            f"{r['completed']} | {r['rejected']} | {failed} | {shed} | "
            f"{dl_shed} | {late} | {wait} | "
            f"{r['mesh']} | "
            f"{r['goodput_tok_s']} | "
            f"{r['ttft_p50_ms']}/{r['ttft_p99_ms']}/{r['ttft_p999_ms']} | "
            f"{ptl} | "
            f"{r['peak_queue_depth']} | {peak_blocks} | "
            f"{spec} | {acc} | {mal} | {draft_s} | {kv} | {pfx_hit} | "
            f"{pfx_tok} | {repl} | {fo} | {hedge} | {degrade} |"
        )
    lines.append("")
    # the capacity planner's durable record lives next to the report —
    # regenerating SERVING.md from serving_*.json must not drop the
    # published capacity curve (docs/autotune.md)
    cap_path = out / "capacity.json"
    if cap_path.exists():
        try:
            cap = json.loads(cap_path.read_text())
        except (OSError, json.JSONDecodeError):
            cap = None
        if cap:
            lines.extend(_capacity_lines(cap))
    atomic_write_text("\n".join(lines), out / "SERVING.md")
    return rows


def _capacity_lines(report: dict[str, Any]) -> list[str]:
    """Markdown section for one capacity-planner report
    (``dlbb_capacity_v1``, ``cli plan --capacity``)."""
    trace = report.get("trace", {})
    lines = [
        "## Fleet capacity curve",
        "",
        f"cm2-predicted vs measured per-replica serving capacity "
        f"(`cli plan --capacity`, docs/autotune.md).  SLO = TTFT within "
        f"{report.get('slo_s', '?')} s (the trace's `deadline_s`); one "
        f"**measured** run per plotted plan on the seeded "
        f"{trace.get('kind', '?')} trace "
        f"(n={trace.get('num_requests', '?')}, "
        f"seed={trace.get('seed', '?')}); a user issues "
        f"{report.get('user_rate_req_per_s', '?')} req/s of "
        f"~{report.get('mean_output_tokens', '?')} output tokens.  "
        f"Replica scaling is linear extrapolation (independent engines "
        f"behind round-robin admission) anchored at the measured "
        f"single-replica numbers.",
        "",
        "| plan | pred tok/s | meas tok/s | pred TTFT ms | "
        "meas TTFT p50 ms | done | SLO ok |",
        "|---|---|---|---|---|---|---|",
    ]
    for p in report.get("plans", []):
        lines.append(
            f"| {p['plan']} | "
            f"{p['predicted_goodput_tokens_per_s']:.0f} | "
            f"{p['measured_goodput_tokens_per_s']:.0f} | "
            f"{p['predicted_ttft_s'] * 1e3:.1f} | "
            f"{p['measured_ttft_p50_s'] * 1e3:.1f} | "
            f"{p['completed']}/{p['total']} | "
            f"{'yes' if p['slo_attainable'] else 'NO'} |"
        )
    users = [c["users"] for c in
             (report.get("plans") or [{}])[0].get("curve", [])]
    if users:
        lines += [
            "",
            "Replicas needed to serve N users within SLO "
            "(predicted / measured; `—` = the plan's TTFT blows the "
            "SLO at any replica count):",
            "",
            "| plan | " + " | ".join(f"N={n}" for n in users) + " |",
            "|---|" + "---|" * len(users),
        ]
        for p in report.get("plans", []):
            cells = []
            for c in p.get("curve", []):
                rp = c.get("replicas_predicted")
                rm = c.get("replicas_measured")
                cells.append(f"{rp if rp is not None else '—'} / "
                             f"{rm if rm is not None else '—'}")
            lines.append(f"| {p['plan']} | " + " | ".join(cells) + " |")
    lines.append("")
    return lines


def publish_capacity_curve(report: dict[str, Any],
                           output_dir: "str | Path" = "stats/serving",
                           ) -> Path:
    """Publish the capacity curve into the serving report tree: persists
    ``capacity.json`` (the durable record ``write_serving_report`` folds
    back in on every regeneration) and rewrites ``SERVING.md`` in place
    — appending the section when the report exists, emitting a minimal
    standalone report otherwise."""
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    from dlbb_tpu.utils.config import save_json

    save_json(report, out / "capacity.json")
    md = out / "SERVING.md"
    if md.exists():
        body = md.read_text().splitlines()
        try:
            cut = body.index("## Fleet capacity curve")
            while cut > 0 and body[cut - 1] == "":
                cut -= 1
            body = body[:cut]
        except ValueError:
            pass
        while body and body[-1] == "":
            body.pop()
        body.append("")
    else:
        body = ["# Serving benchmark report", ""]
    body.extend(_capacity_lines(report))
    atomic_write_text("\n".join(body), md)
    return md


def write_fastpath_report(bench_path: "str | Path",
                          output_dir: "str | Path") -> list[dict[str, Any]]:
    """The fast-path vs baseline comparison table: consolidate
    ``BENCH_serve.json`` (``scripts/bench_serving.py`` — per-step vs
    fused-K x compaction over the same replayed trace) into
    ``FASTPATH.md``.  Returns the rows (empty when the bench artifact
    is missing/unreadable — callers skip, never clobber)."""
    bench_path = Path(bench_path)
    try:
        bench = json.loads(bench_path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    settings = bench.get("settings", {})
    if not settings:
        return []
    base_key = bench.get("baseline", "per_step")
    rows = []
    for name in settings:
        s = settings[name]
        tps = s.get("output_tokens_per_s", {})
        med = tps.get("median")
        # prefer the bench's own (within-mesh, within-trace) speedup;
        # fall back to the global baseline for older artifacts
        speedup = s.get("speedup_vs_per_step")
        if speedup is None:
            base = settings.get(s.get("baseline", base_key), {})
            base_tps = base.get("output_tokens_per_s", {}).get("median")
            speedup = (round(med / base_tps, 3)
                       if med and base_tps else None)
        rows.append({
            "setting": name,
            "baseline": s.get("baseline", base_key),
            "trace": s.get("trace"),
            "decode_horizon": s.get("decode_horizon"),
            "compaction": s.get("compact_threshold") is not None,
            "output_tok_s_median": med,
            "output_tok_s_min": tps.get("min"),
            "output_tok_s_max": tps.get("max"),
            "per_token_p50_ms": s.get("per_token_p50_ms"),
            "decode_units": s.get("decode_units"),
            "speedup_vs_baseline": speedup,
        })
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    lines = [
        "# Decode fast path vs per-step baseline",
        "",
        f"Source: `{bench_path.name}` "
        "(`scripts/bench_serving.py` — every setting replays the SAME "
        "seeded trace as its baseline, settings interleaved within "
        "each repetition so host drift cancels; medians of per-rep "
        "throughput with min/max spread).  Throughput is generated "
        "output tokens per wall second; each speedup is against the "
        "per-step PR-9 engine on the SAME mesh and trace "
        f"(default `{base_key}`).",
        "",
        "| setting | trace | K | compaction | out tok/s (min..max) | "
        "tok p50 ms | decode units | speedup vs baseline |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        tps = ("-" if r["output_tok_s_median"] is None else
               f"{r['output_tok_s_median']:.0f} "
               f"({r['output_tok_s_min']:.0f}..{r['output_tok_s_max']:.0f})")
        speed = ("-" if r["speedup_vs_baseline"] is None
                 else f"{r['speedup_vs_baseline']:.2f}x")
        lines.append(
            f"| {r['setting']} | {r['trace'] or '-'} | "
            f"{r['decode_horizon']} | "
            f"{'on' if r['compaction'] else 'off'} | {tps} | "
            f"{r['per_token_p50_ms']} | {r['decode_units']} | {speed} |"
        )
    lines.append("")
    atomic_write_text("\n".join(lines), out / "FASTPATH.md")
    return rows


def write_speculative_report(bench_path: "str | Path",
                             output_dir: "str | Path"
                             ) -> list[dict[str, Any]]:
    """The speculative-decoding comparison table: consolidate
    ``BENCH_spec.json`` (``scripts/bench_speculative.py`` — {off, ngram
    γ ladder, draft-model} x {per-step, fused K16} over the same
    repeating-structure seeded trace) into ``SPECULATIVE.md``.  Returns
    the rows (empty when the bench artifact is missing/unreadable —
    callers skip, never clobber)."""
    bench_path = Path(bench_path)
    try:
        bench = json.loads(bench_path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    settings = bench.get("settings", {})
    if not settings:
        return []
    base_key = bench.get("baseline", "off_fused16")
    base_med = (settings.get(base_key, {})
                .get("output_tokens_per_s", {}).get("median"))
    rows = []
    for name, s in settings.items():
        tps = s.get("output_tokens_per_s", {})
        med = tps.get("median")
        speedup = s.get("speedup_vs_baseline")
        if speedup is None and med and base_med:
            speedup = round(med / base_med, 3)
        rows.append({
            "setting": name,
            "speculation": s.get("speculation"),
            "spec_gamma": s.get("spec_gamma"),
            "decode_horizon": s.get("decode_horizon"),
            "output_tok_s_median": med,
            "output_tok_s_min": tps.get("min"),
            "output_tok_s_max": tps.get("max"),
            "ttft_p50_ms": s.get("ttft_p50_ms"),
            "per_token_p50_ms": s.get("per_token_p50_ms"),
            "acceptance_rate": s.get("acceptance_rate"),
            "mean_accepted_len": s.get("mean_accepted_len"),
            "draft_overhead_s": s.get("draft_overhead_s"),
            "token_identical": s.get("token_identical"),
            "speedup_vs_baseline": speedup,
            "status": s.get("status", "ok"),
        })
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    lines = [
        "# Speculative decoding vs the fused-scan fast path",
        "",
        f"Source: `{bench_path.name}` "
        "(`scripts/bench_speculative.py` — every setting replays the "
        "SAME repeating-structure seeded trace, settings interleaved "
        "within each repetition so host drift cancels; medians of "
        "per-rep throughput with min/max spread).  Throughput is "
        "COMPLETED output tokens per wall second; each speedup is "
        "regime-matched — per-step rows price against the "
        "non-speculative per-step engine, fused rows against the "
        f"non-speculative fused scan (`{base_key}`), each row's "
        "`baseline` key in the artifact names which — so the column "
        "answers \"what does drafting buy on top of the engine you "
        "already run\".  \"identical\" is the greedy "
        "token-identity gate: the setting's completed token sequences "
        "matched the per-step oracle engine's, re-checked by the bench "
        "before publishing (a failed gate marks the row and the bench "
        "exits nonzero).  Acceptance is drafted-tokens-accepted / "
        "drafted; \"acc len\" is mean tokens committed per verify unit "
        "(docs/serving.md, \"Speculative decoding\").  Sim-mesh rows "
        "measure the dispatch-overhead regime honestly: the verify "
        "unit's host sync is priced in, so chip-regime gains (one "
        "weights-bound forward per γ+1 tokens) are larger than what "
        "the CPU-simulated mesh shows.",
        "",
        "| setting | drafter | γ | K | out tok/s (min..max) | "
        "TTFT p50 ms | tok p50 ms | acc | acc len | draft s | "
        "identical | speedup |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        tps = ("-" if r["output_tok_s_median"] is None else
               f"{r['output_tok_s_median']:.0f} "
               f"({r['output_tok_s_min']:.0f}.."
               f"{r['output_tok_s_max']:.0f})")
        speed = ("-" if r["speedup_vs_baseline"] is None
                 else f"{r['speedup_vs_baseline']:.2f}x")
        acc = ("-" if r["acceptance_rate"] is None
               else f"{r['acceptance_rate']:.2f}")
        mal = ("-" if r["mean_accepted_len"] is None
               else f"{r['mean_accepted_len']:.2f}")
        draft_s = ("-" if r["draft_overhead_s"] is None
                   else f"{r['draft_overhead_s']:.3f}")
        ident = ("-" if r["token_identical"] is None
                 else ("yes" if r["token_identical"] else "NO"))
        if r["status"] == "pending_tunnel":
            tps, speed = "pending_tunnel", "-"
        lines.append(
            f"| {r['setting']} | {r['speculation'] or '-'} | "
            f"{r['spec_gamma'] or '-'} | {r['decode_horizon'] or 1} | "
            f"{tps} | {r['ttft_p50_ms']} | {r['per_token_p50_ms']} | "
            f"{acc} | {mal} | {draft_s} | {ident} | {speed} |"
        )
    lines.append("")
    atomic_write_text("\n".join(lines), out / "SPECULATIVE.md")
    return rows


def write_fleet_report(bench_path: "str | Path",
                       output_dir: "str | Path") -> list[dict[str, Any]]:
    """The fleet fault-tolerance table: consolidate ``BENCH_fleet.json``
    (``scripts/bench_fleet.py`` — single-engine oracle vs clean 2-replica
    fleet vs replica-killed fleet over the same seeded trace) into
    ``FLEET.md``.  Returns the rows (empty when the bench artifact is
    missing/unreadable — callers skip, never clobber)."""
    bench_path = Path(bench_path)
    try:
        bench = json.loads(bench_path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    settings = bench.get("settings", {})
    if not settings:
        return []
    rows = []
    for name, s in settings.items():
        tps = s.get("goodput_tokens_per_s", {})
        fo = s.get("failovers", {})
        rows.append({
            "setting": name,
            "goodput_median": tps.get("median"),
            "goodput_min": tps.get("min"),
            "goodput_max": tps.get("max"),
            "ttft_p50_ms": s.get("ttft_p50_ms"),
            "ttft_p99_ms": s.get("ttft_p99_ms"),
            "failovers": fo.get("median"),
            "token_identical": s.get("token_identical"),
        })
    failover = bench.get("failover", {})
    pen = failover.get("ttft_penalty_ms", {})
    fleet = bench.get("fleet", {})
    trace = bench.get("trace", {})
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    lines = [
        "# Replica-level fault tolerance: the cost of a failover",
        "",
        f"Source: `{bench_path.name}` "
        "(`scripts/bench_fleet.py` — the SAME seeded "
        f"{trace.get('kind', '?')} trace "
        f"(n={trace.get('requests', '?')}, seed={trace.get('seed', '?')}) "
        "through a single replica-sized engine (the token oracle), a "
        f"clean {fleet.get('replicas', '?')}-replica fleet, and the same "
        "fleet with `serve-replica-kill` fired mid-trace; settings "
        "interleaved within each repetition, medians with min/max "
        "spread; docs/fleet.md).  Every fleet run — clean AND killed — "
        "is gated token-identical to the oracle before publishing, so "
        "the penalty prices recovery of the SAME answer, not a "
        "different one.  The TTFT penalty is failed-over minus clean "
        "requests WITHIN the kill run (queueing drift between runs "
        "cancels).",
        "",
        "| setting | goodput tok/s (min..max) | TTFT p50 ms | "
        "TTFT p99 ms | failovers | identical |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        tps = ("-" if r["goodput_median"] is None else
               f"{r['goodput_median']:.0f} "
               f"({r['goodput_min']:.0f}..{r['goodput_max']:.0f})")
        fo = "-" if r["failovers"] is None else r["failovers"]
        ident = ("-" if r["token_identical"] is None
                 else ("yes" if r["token_identical"] else "NO"))
        lines.append(
            f"| {r['setting']} | {tps} | {r['ttft_p50_ms']} | "
            f"{r['ttft_p99_ms']} | {fo} | {ident} |"
        )
    if pen:
        lines += [
            "",
            f"**Failover TTFT penalty: {pen.get('median', '?')} ms** "
            f"({pen.get('min', '?')}..{pen.get('max', '?')} across "
            f"reps), {failover.get('failovers_per_run', {}).get('median', '?')} "
            "failover(s) per kill run; goodput retained "
            f"**{failover.get('goodput_retained_vs_clean_fleet', '?')}x** "
            "vs the unfaulted fleet.",
        ]
    lines.append("")
    atomic_write_text("\n".join(lines), out / "FLEET.md")
    return rows


def write_prefix_report(bench_path: "str | Path",
                        output_dir: "str | Path") -> list[dict[str, Any]]:
    """The shared-prefix / quantized-KV comparison table: consolidate
    ``BENCH_prefix.json`` (``scripts/bench_prefix.py`` — prefix-share x
    {none, int8} over the same seeded shared-prefix traces, equivalence
    gate first) into ``PREFIX.md``.  Returns the rows (empty when the
    bench artifact is missing/unreadable — callers skip, never
    clobber)."""
    bench_path = Path(bench_path)
    try:
        bench = json.loads(bench_path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    settings = bench.get("settings", {})
    if not settings:
        return []
    traces = bench.get("traces", {})
    capacity = bench.get("capacity", {})
    acceptance = bench.get("acceptance", {})
    rows = []
    for name, s in settings.items():
        tps = s.get("output_tokens_per_s", {})
        rows.append({
            "setting": name,
            "trace": s.get("trace"),
            "prefix_caching": s.get("prefix_caching"),
            "kv_quantization": s.get("kv_quantization"),
            "output_tok_s_median": tps.get("median"),
            "output_tok_s_min": tps.get("min"),
            "output_tok_s_max": tps.get("max"),
            "ttft_p50_ms": s.get("ttft_p50_ms"),
            "per_token_p50_ms": s.get("per_token_p50_ms"),
            "prefix_hit_rate": s.get("prefix_hit_rate"),
            "tokens_reused": s.get("tokens_reused"),
            "token_identical": s.get("token_identical"),
            "token_identity_fraction": s.get("token_identity_fraction"),
            "baseline": s.get("baseline"),
            "ttft_speedup": s.get("ttft_speedup_vs_baseline"),
            "goodput_speedup": s.get("goodput_speedup_vs_baseline"),
            "status": s.get("status", "ok"),
        })
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    share_note = "; ".join(
        f"`{t}`: {v.get('shared_token_share', 0) * 100:.0f}% shared "
        f"(groups={v.get('prefix_groups')}, "
        f"prefix_len={v.get('prefix_len')})"
        for t, v in sorted(traces.items())) or "-"
    lines = [
        "# Shared-prefix KV cache & quantized KV planes",
        "",
        f"Source: `{bench_path.name}` "
        "(`scripts/bench_prefix.py` — every setting replays the SAME "
        "seeded shared-prefix trace as its baseline, settings "
        "interleaved within each repetition so host drift cancels; "
        "medians of per-rep throughput with min/max spread).  The "
        "equivalence gate runs FIRST on the published traces, against "
        "the no-sharing fp engine: fp prefix-cached settings must be "
        "BIT-EXACT; int8 settings are gated within tolerance (a "
        "minimum fraction of requests fully token-identical — one "
        "flipped argmax diverges the rest of that request's greedy "
        "feedback, so the per-request fraction is the honest scalar, "
        "shown in \"identical\").  TTFT is arrival-to-first-token; each "
        "speedup is against the prefix-off fp engine on the SAME mesh "
        "and trace.  \"hit\" is prefix-cache attaches / prefills, "
        "\"reused\" the prompt tokens whose prefill was skipped by "
        "attaching refcounted donor blocks "
        "(docs/serving.md, \"Prefix cache & quantized KV\").  "
        f"Traces: {share_note}.",
        "",
        "| setting | trace | prefix | kv | out tok/s (min..max) | "
        "TTFT p50 ms | tok p50 ms | hit | reused | identical | "
        "TTFT speedup | goodput speedup |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        tps = ("-" if r["output_tok_s_median"] is None else
               f"{r['output_tok_s_median']:.0f} "
               f"({r['output_tok_s_min']:.0f}.."
               f"{r['output_tok_s_max']:.0f})")
        hit = ("-" if r["prefix_hit_rate"] is None
               else f"{r['prefix_hit_rate'] * 100:.0f}%")
        reused = "-" if r["tokens_reused"] is None else r["tokens_reused"]
        # fp rows are gated bit-exact (yes/NO); int8 rows are gated
        # within tolerance — show the per-request identity fraction
        frac = r["token_identity_fraction"]
        if r["token_identical"] is None:
            ident = "-"
        elif r["token_identical"]:
            ident = "yes"
        elif frac is not None:
            ident = f"{frac * 100:.0f}% reqs"
        else:
            ident = "NO"
        tsp = ("-" if r["ttft_speedup"] is None
               else f"{r['ttft_speedup']:.2f}x")
        gsp = ("-" if r["goodput_speedup"] is None
               else f"{r['goodput_speedup']:.2f}x")
        if r["status"] == "pending_tunnel":
            tps, tsp, gsp = "pending_tunnel", "-", "-"
        lines.append(
            f"| {r['setting']} | {r['trace'] or '-'} | "
            f"{'on' if r['prefix_caching'] else 'off'} | "
            f"{r['kv_quantization'] or 'none'} | {tps} | "
            f"{r['ttft_p50_ms']} | {r['per_token_p50_ms']} | "
            f"{hit} | {reused} | {ident} | {tsp} | {gsp} |"
        )
    if capacity:
        res = capacity.get("resident_requests", {})
        per_req = capacity.get("per_request_bytes_per_device", {})
        lines += [
            "",
            "## Static capacity under the HBM budget",
            "",
            "Priced by `kv_cache_bytes_per_device` (the same formula "
            "the build-time budget gate and the static memory audit's "
            "`serving-cache-drift` pin cross-check against the "
            "compiled decode carry — not a separate estimate): "
            "resident requests admissible under "
            f"`hbm_budget_gb={capacity.get('hbm_budget_gb')}` at "
            f"max_seq={capacity.get('max_seq')}, "
            f"block_size={capacity.get('block_size')}, "
            f"mesh dp{capacity.get('dp', 1)} x tp{capacity.get('tp')}.",
            "",
            "| kv layout | bytes/request/device | resident requests |",
            "|---|---|---|",
            f"| none (fp32) | {per_req.get('none')} | "
            f"{res.get('none')} |",
            f"| int8 + fp32 scales | {per_req.get('int8')} | "
            f"{res.get('int8')} |",
            "",
            f"Capacity ratio: **{capacity.get('capacity_ratio')}x** "
            f"(bar >= {capacity.get('min_ratio')}x: "
            f"{'PASS' if capacity.get('passed') else 'FAIL'}).",
        ]
    checks = []
    ttft_acc = acceptance.get("ttft", {})
    if ttft_acc:
        checks.append(
            f"TTFT p50 `{ttft_acc.get('setting')}` vs "
            f"`{ttft_acc.get('baseline')}`: "
            f"{ttft_acc.get('measured_speedup')}x "
            f"(bar >= {ttft_acc.get('min_speedup')}x: "
            f"{'PASS' if ttft_acc.get('passed') else 'FAIL'})")
    cap_acc = acceptance.get("capacity", {})
    if cap_acc:
        checks.append(
            f"int8 resident-request capacity: "
            f"{cap_acc.get('measured_ratio')}x "
            f"(bar >= {cap_acc.get('min_ratio')}x: "
            f"{'PASS' if cap_acc.get('passed') else 'FAIL'})")
    if checks:
        lines += ["", "## Checked claims", ""]
        lines += [f"- {c}" for c in checks]
    lines.append("")
    atomic_write_text("\n".join(lines), out / "PREFIX.md")
    return rows
