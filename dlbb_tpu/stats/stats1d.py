"""1D microbenchmark statistics.

Schema parity with reference ``collectives/1d/stats.py``: per-file stats in
µs (mean/median/min/max/std/p95/p99), load-imbalance % over per-rank means
(:54-61), bus bandwidth GB/s from the *max* time (conservative choice,
:178-186), per-file ``*_stats.json`` and a consolidated
``benchmark_statistics.csv`` with the same columns (:226-241) plus one
trailing ``timing_granularity`` extension column (the 3D *standard* CSV,
whose header is asserted byte-identical to the reference's, instead puts
the marker in the transposed CSV's metadata block — see ``stats3d``).

The reference's bandwidth formula is uniform across all eight ops
(``elements x element_size x num_ranks / time / 2**30`` — :98-121, a
documented quirk, SURVEY "known quirks").  We keep it as the default for
curve comparability and offer ``algorithm_bandwidth=True`` for the standard
bus-bandwidth factors (e.g. ring allreduce moves ``2(P-1)/P`` bytes/elt).

Differences (documented, not silent):
- element size follows the recorded dtype (the reference hardcodes fp16's
  2 bytes at :93 even for other dtypes);
- per-rank timing rows are per-*host* dispatch timings under SPMD; with one
  process the load-imbalance over a single row is 0 by construction;
- a trailing ``timing_granularity`` CSV column marks rows computed from
  chunked-mode artifacts (``dlbb_tpu/utils/timing.py::time_fn_chained``),
  whose samples are chunk *means*: their p95/p99 measure the spread of
  chunk means, not per-iteration tail latencies, and must not be compared
  against per-iteration tails.  The per-file stats JSON carries the full
  ``percentile_caveat`` text.
"""

from __future__ import annotations

import csv
import io
import json
from pathlib import Path
from typing import Any, Optional

import numpy as np

from dlbb_tpu.utils.config import atomic_write_text

_DTYPE_BYTES = {
    "bfloat16": 2,
    "float16": 2,
    "float32": 4,
    "float64": 8,
    # reference records numpy repr strings like "<class 'numpy.float16'>"
    "<class 'numpy.float16'>": 2,
}

CSV_COLUMNS = [
    "mpi_implementation",
    "operation",
    "num_ranks",
    "data_size_name",
    "num_elements",
    "mean_time_us",
    "median_time_us",
    "min_time_us",
    "max_time_us",
    "std_dev_us",
    "p95_time_us",
    "p99_time_us",
    "load_imbalance_percent",
    "bandwidth_gbps",
    # extension columns (not in the reference):
    # - timing_granularity: "per_iteration" or "chunked(N)" — percentile
    #   columns of chunked rows are over chunk means, not per-iteration tails
    # - dtype: the measured element type; the corpus carries the north-star
    #   curve in BOTH bf16 and fp32 (BASELINE.json configs[1]), so rows are
    #   keyed by (op, size, ranks, dtype)
    # - bytes_on_wire: analytic per-device wire bytes of the op's
    #   implementation (dlbb_tpu.analysis.expectations.op_wire_bytes;
    #   blank for ops without a wire model).  bandwidth_gbps stays the
    #   reference's LOGICAL-payload formula, so compressed-vs-uncompressed
    #   curves normalise by logical bytes and this column shows the wire
    #   saving (docs/compression.md)
    "timing_granularity",
    "dtype",
    "bytes_on_wire",
]


def calculate_statistics(timings_2d: list[list[float]]) -> dict[str, Any]:
    """Aggregate stats (µs) + load imbalance over per-rank means
    (reference ``collectives/1d/stats.py:26-75``)."""
    from dlbb_tpu.native import load_imbalance_native, row_means_native

    arr = np.asarray(timings_2d, dtype=np.float64)
    rm = row_means_native(arr)
    per_rank_means = rm if rm is not None else arr.mean(axis=1)
    flat = arr.ravel()
    li = load_imbalance_native(per_rank_means)
    if li is not None:
        load_imbalance = li
    else:
        mean_of_means = per_rank_means.mean()
        load_imbalance = (
            (per_rank_means.max() - mean_of_means) / mean_of_means * 100.0
            if mean_of_means > 0
            else 0.0
        )
    return {
        "mean_time_us": float(flat.mean() * 1e6),
        "median_time_us": float(np.median(flat) * 1e6),
        "min_time_us": float(flat.min() * 1e6),
        "max_time_us": float(flat.max() * 1e6),
        "std_dev_us": float(flat.std() * 1e6),
        "p95_time_us": float(np.percentile(flat, 95) * 1e6),
        "p99_time_us": float(np.percentile(flat, 99) * 1e6),
        "load_imbalance_percent": float(load_imbalance),
        "per_rank_means_us": (per_rank_means * 1e6).tolist(),
    }


# Logical bytes moved per element, as a multiple of (element_size), for the
# standard bus-bandwidth accounting (cf. nccl-tests bus bandwidth).
def _algo_volume_factor(operation: str, p: int) -> float:
    if operation in ("allreduce",):
        return 2.0 * (p - 1) / p * p  # 2(P-1) x elements x size total
    if operation in ("allgather", "reducescatter", "alltoall"):
        return float(p - 1)
    if operation in ("broadcast", "gather", "scatter", "reduce"):
        return float(p - 1)
    if operation == "sendrecv":
        return float(p)
    return float(p)


def calculate_bandwidth(
    num_elements: int,
    dtype: str,
    time_seconds: float,
    operation: str,
    num_ranks: int,
    algorithm_bandwidth: bool = False,
) -> Optional[float]:
    """Bus bandwidth in GB/s (GiB-based divisor, like the reference :124)."""
    if time_seconds <= 0:
        return None
    element_size = _DTYPE_BYTES.get(dtype, 2)
    if algorithm_bandwidth:
        volume = num_elements * element_size * _algo_volume_factor(
            operation, num_ranks
        )
    else:
        # reference's uniform formula (:98-121)
        volume = num_elements * element_size * num_ranks
    return float(volume / time_seconds / 2**30)


def process_file(
    json_path: Path, algorithm_bandwidth: bool = False
) -> dict[str, Any]:
    with open(json_path) as f:
        data = json.load(f)
    impl = (
        data.get("mpi_implementation")
        or data.get("implementation")
        or "unknown"
    )
    stats = calculate_statistics(data["timings"])
    bandwidth = calculate_bandwidth(
        data["num_elements"],
        data.get("dtype", "bfloat16"),
        stats["max_time_us"] / 1e6,
        data["operation"],
        data["num_ranks"],
        algorithm_bandwidth=algorithm_bandwidth,
    )
    # analytic wire volume (dlbb_tpu.analysis.expectations — jax-free, so
    # the stats path stays backend-free): lets compressed-vs-uncompressed
    # bus-bandwidth curves normalise by LOGICAL payload bytes (the
    # bandwidth column above) while still showing the wire saving
    from dlbb_tpu.analysis.expectations import op_wire_bytes

    wire = op_wire_bytes(
        data["operation"], data["num_elements"], data["num_ranks"],
        _DTYPE_BYTES.get(data.get("dtype", "bfloat16"), 2),
        compression=data.get("compression"),
    )
    out = {
        "mpi_implementation": impl,
        "operation": data["operation"],
        "num_ranks": data["num_ranks"],
        "data_size_name": data.get("data_size_name", ""),
        "num_elements": data["num_elements"],
        "dtype": data.get("dtype", ""),
        **stats,
        "bandwidth_gbps": bandwidth,
        "bytes_on_wire": wire,
        # reference artifacts (and per_iter runs) have no granularity
        # marker: their timing rows are genuine per-iteration samples
        "timing_granularity": data.get("timing_granularity",
                                       "per_iteration"),
        # measured backend ("cpu" = simulated mesh) — consumed by the
        # comparison's not_comparable(simulated) verdict; reference
        # artifacts record no system_info and get None
        "backend": (data.get("system_info") or {}).get("backend"),
    }
    if "percentile_caveat" in data:
        out["percentile_caveat"] = data["percentile_caveat"]
    return out


def process_1d_results(
    input_dir: str | Path,
    output_dir: str | Path,
    csv_name: str = "benchmark_statistics.csv",
    algorithm_bandwidth: bool = False,
    verbose: bool = True,
) -> list[dict[str, Any]]:
    """Process every result JSON in ``input_dir`` → per-file ``*_stats.json``
    + consolidated CSV in ``output_dir`` (reference
    ``collectives/1d/stats.py:135-250``).  Idempotent, like the reference's
    recompute-from-artifacts model (SURVEY §5.4)."""
    input_dir, output_dir = Path(input_dir), Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    results = []
    for json_file in sorted(input_dir.glob("*.json")):
        if json_file.name.endswith("_stats.json"):
            continue
        try:
            result = process_file(json_file, algorithm_bandwidth)
        except Exception as e:  # noqa: BLE001 — per-file resilience (:204)
            if verbose:
                print(f"  ERROR processing {json_file.name}: {e}")
            continue
        out = output_dir / (json_file.stem + "_stats.json")
        # atomic (tmp + fsync + os.replace): a killed stats pass must not
        # leave a torn *_stats.json that the next report run would parse
        atomic_write_text(json.dumps(result, indent=2), out)
        results.append(result)

    if results:
        buf = io.StringIO()
        writer = csv.DictWriter(buf, fieldnames=CSV_COLUMNS)
        writer.writeheader()
        for r in results:
            writer.writerow(
                {
                    k: v
                    for k, v in r.items()
                    if k not in ("per_rank_means_us",
                                 "percentile_caveat", "backend")
                }
            )
        atomic_write_text(buf.getvalue(), output_dir / csv_name, newline="")
        if verbose:
            print(f"Consolidated CSV saved: {output_dir / csv_name}")
    return results
