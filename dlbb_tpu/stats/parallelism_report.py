"""Parallelism-family benchmark comparison — measured tables for the
framework's flagship extensions.

The reference's ethos is that every tuning axis ends in a results
directory (``collectives/3d/launch_dsccl.sh:34-65`` → 19 result dirs);
round 3 left the parallelism extensions — pipeline schedules, context
parallelism, MoE dispatch — with correctness tests and dryrun phases but
no committed step-time numbers (VERDICT r3 missing #4).  This module
joins the ``results/parallelism/`` train artifacts (produced by the
publisher's ``parallelism`` stage on the simulated 8-device mesh) into a
per-family comparison: GPipe vs 1F1B, ring vs Ulysses, MoE dense vs
capacity dispatch, each pair measured at an identical config except for
the axis under test.

Simulated-mesh caveat (same as the collective corpus): absolute times are
host-core times, not ICI; WITHIN a family the members run the same FLOPs
on the same mesh, so the relative ordering is the honest signal.
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Any, Optional

COLUMNS = [
    "family", "member", "experiment", "mesh", "step_time_mean_s",
    "tokens_per_second", "winner", "slowdown_vs_winner",
]

# The benchmark matrix: each family is a pair identical except for the
# axis under test.  Single source of truth for the artifact producer
# (scripts/publish_baselines.py stage "parallelism") and the report CLI.
DEFAULT_FAMILIES: dict[str, list[str]] = {
    "pipeline_schedule": ["pp2_gpipe", "pp2_1f1b"],
    "context_parallel": ["sp2_ring", "sp2_ulysses"],
    "moe_dispatch": ["ep2_moe_dense", "ep2_moe_capacity"],
    # the reshard cost behind train/loop.py's grad-accum x dp warning:
    # same model/mesh/grad_accum, batch 16 keeps micro-batches divisible
    # by dp=4, batch 20 forces the per-micro-step reshard — per-TOKEN
    # throughput is the comparison (batches differ by construction)
    "grad_accum_reshard": ["ga2_divisible_b16", "ga2_reshard_b20"],
}


def collect_family_rows(
    results_dir: Path, families: dict[str, list[str]]
) -> list[dict[str, Any]]:
    """One row per family member, joined from the train artifacts.

    ``families``: {family: [experiment names]}; members whose artifact is
    missing are listed with null times (absence is honest, not silent).
    """
    results_dir = Path(results_dir)
    artifacts: dict[str, dict] = {}
    for f in sorted(results_dir.glob("train_*.json")):
        try:
            r = json.loads(f.read_text())
        except Exception:  # noqa: BLE001 — per-file resilience
            continue
        name = r.get("experiment", {}).get("name")
        if name:
            artifacts[name] = r

    rows: list[dict[str, Any]] = []
    for family, members in families.items():
        present = {
            m: artifacts[m] for m in members if m in artifacts
        }
        # winner by tokens/s, not raw step time: most families run equal
        # batches (same ordering either way), but e.g. the grad-accum
        # reshard pair intentionally differs in batch size — per-token
        # throughput is the comparable metric
        # single winner by identity (first member in declared order at
        # the max) — float-equality ties would otherwise mark several
        # rows winner and render slowdown_vs_winner ambiguously
        best_member: Optional[str] = (
            max(present, key=lambda m: present[m]["tokens_per_second"])
            if present else None
        )
        best: Optional[float] = (
            present[best_member]["tokens_per_second"]
            if best_member is not None else None
        )
        for m in members:
            r = present.get(m)
            if r is None:
                rows.append({
                    "family": family, "member": m, "experiment": m,
                    "mesh": None, "step_time_mean_s": None,
                    "tokens_per_second": None, "winner": None,
                    "slowdown_vs_winner": None,
                })
                continue
            tps = r["tokens_per_second"]
            rows.append({
                "family": family,
                "member": m,
                "experiment": m,
                "mesh": "x".join(
                    f"{k}{v}" for k, v in r["mesh"].items() if v > 1
                ) or "single",
                "step_time_mean_s": round(r["step_time"]["mean"], 6),
                "tokens_per_second": round(tps, 1),
                "winner": m == best_member,
                "slowdown_vs_winner": round(best / tps, 4),
            })
    return rows


def write_parallelism_report(
    results_dir: Path,
    out_dir: Path,
    families: dict[str, list[str]],
) -> list[dict[str, Any]]:
    """Emit ``parallelism_comparison.csv`` + ``PARALLELISM.md``; returns
    the rows."""
    rows = collect_family_rows(results_dir, families)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    with (out_dir / "parallelism_comparison.csv").open(
        "w", newline=""
    ) as f:
        w = csv.DictWriter(f, fieldnames=COLUMNS)
        w.writeheader()
        for r in rows:
            w.writerow(r)

    md = [
        "# Parallelism-family benchmarks (simulated 8-device mesh)",
        "",
        "Step-time comparison of the framework's parallelism extensions, "
        "each family measured at an identical config except for the axis "
        "under test (`results/parallelism/` artifacts; producer: "
        "`scripts/publish_baselines.py --stage parallelism`).",
        "",
        "Absolute times are single-host-core simulation times, not ICI "
        "(same caveat as the collective corpus); within a family the "
        "members run the same model on the same mesh, so the *relative* "
        "ordering is the signal.",
        "",
    ]
    from dlbb_tpu.stats.compare import md_table

    md += md_table(rows, COLUMNS)
    md.append("")
    (out_dir / "PARALLELISM.md").write_text("\n".join(md))
    return rows
