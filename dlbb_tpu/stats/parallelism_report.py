"""Parallelism-family benchmark comparison — measured tables for the
framework's flagship extensions.

The reference's ethos is that every tuning axis ends in a results
directory (``collectives/3d/launch_dsccl.sh:34-65`` → 19 result dirs);
round 3 left the parallelism extensions — pipeline schedules, context
parallelism, MoE dispatch — with correctness tests and dryrun phases but
no committed step-time numbers (VERDICT r3 missing #4).  This module
joins the ``results/parallelism/`` train artifacts (produced by the
publisher's ``parallelism`` stage on the simulated 8-device mesh) into a
per-family comparison: GPipe vs 1F1B, ring vs Ulysses, MoE dense vs
capacity dispatch, each pair measured at an identical config except for
the axis under test.

Simulated-mesh caveat (same as the collective corpus): absolute times are
host-core times, not ICI; WITHIN a family the members run the same FLOPs
on the same mesh, so the relative ordering is the honest signal.
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Any, Optional

COLUMNS = [
    "family", "member", "experiment", "mesh", "step_time_mean_s",
    "tokens_per_second", "winner", "slowdown_vs_winner",
]

# The benchmark matrix: each family is a pair identical except for the
# axis under test.  Single source of truth for the artifact producer
# (scripts/publish_baselines.py stage "parallelism") and the report CLI.
DEFAULT_FAMILIES: dict[str, list[str]] = {
    "pipeline_schedule": ["pp2_gpipe", "pp2_1f1b"],
    "context_parallel": ["sp2_ring", "sp2_ulysses"],
    "moe_dispatch": ["ep2_moe_dense", "ep2_moe_capacity"],
    # the reshard cost behind train/loop.py's grad-accum x dp warning:
    # same model/mesh/grad_accum, batch 16 keeps micro-batches divisible
    # by dp=4, batch 20 forces the per-micro-step reshard — per-TOKEN
    # throughput is the comparison (batches differ by construction)
    "grad_accum_reshard": ["ga2_divisible_b16", "ga2_reshard_b20"],
}


def collect_family_rows(
    results_dir: Path, families: dict[str, list[str]]
) -> list[dict[str, Any]]:
    """One row per family member, joined from the train artifacts.

    ``families``: {family: [experiment names]}; members whose artifact is
    missing are listed with null times (absence is honest, not silent).
    """
    results_dir = Path(results_dir)
    artifacts: dict[str, dict] = {}
    for f in sorted(results_dir.glob("train_*.json")):
        try:
            r = json.loads(f.read_text())
        except Exception:  # noqa: BLE001 — per-file resilience
            continue
        name = r.get("experiment", {}).get("name")
        if name:
            artifacts[name] = r

    rows: list[dict[str, Any]] = []
    for family, members in families.items():
        present = {
            m: artifacts[m] for m in members if m in artifacts
        }
        # winner by tokens/s, not raw step time: most families run equal
        # batches (same ordering either way), but e.g. the grad-accum
        # reshard pair intentionally differs in batch size — per-token
        # throughput is the comparable metric
        # single winner by identity (first member in declared order at
        # the max) — float-equality ties would otherwise mark several
        # rows winner and render slowdown_vs_winner ambiguously
        best_member: Optional[str] = (
            max(present, key=lambda m: present[m]["tokens_per_second"])
            if present else None
        )
        best: Optional[float] = (
            present[best_member]["tokens_per_second"]
            if best_member is not None else None
        )
        for m in members:
            r = present.get(m)
            if r is None:
                rows.append({
                    "family": family, "member": m, "experiment": m,
                    "mesh": None, "step_time_mean_s": None,
                    "tokens_per_second": None, "winner": None,
                    "slowdown_vs_winner": None,
                })
                continue
            tps = r["tokens_per_second"]
            rows.append({
                "family": family,
                "member": m,
                "experiment": m,
                "mesh": "x".join(
                    f"{k}{v}" for k, v in r["mesh"].items() if v > 1
                ) or "single",
                "step_time_mean_s": round(r["step_time"]["mean"], 6),
                "tokens_per_second": round(tps, 1),
                "winner": m == best_member,
                "slowdown_vs_winner": round(best / tps, 4),
            })
    return rows


def write_parallelism_report(
    results_dir: Path,
    out_dir: Path,
    families: dict[str, list[str]],
) -> list[dict[str, Any]]:
    """Emit ``parallelism_comparison.csv`` + ``PARALLELISM.md``; returns
    the rows."""
    rows = collect_family_rows(results_dir, families)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    with (out_dir / "parallelism_comparison.csv").open(
        "w", newline=""
    ) as f:
        w = csv.DictWriter(f, fieldnames=COLUMNS)
        w.writeheader()
        for r in rows:
            w.writerow(r)

    md = [
        "# Parallelism-family benchmarks (simulated 8-device mesh)",
        "",
        "Step-time comparison of the framework's parallelism extensions, "
        "each family measured at an identical config except for the axis "
        "under test (`results/parallelism/` artifacts; producer: "
        "`scripts/publish_baselines.py --stage parallelism`).",
        "",
        "Absolute times are single-host-core simulation times, not ICI "
        "(same caveat as the collective corpus); within a family the "
        "members run the same model on the same mesh, so the *relative* "
        "ordering is the signal.",
        "",
    ]
    from dlbb_tpu.stats.compare import md_table

    md += md_table(rows, COLUMNS)
    md.append("")
    (out_dir / "PARALLELISM.md").write_text("\n".join(md))
    return rows


CP_COLUMNS = [
    "seq_len", "sp", "ring_tokens_per_second", "ulysses_tokens_per_second",
    "winner", "ring_over_ulysses",
]


def collect_cp_scaling_rows(results_dir: Path) -> list[dict[str, Any]]:
    """One row per (S, sp) cell of the long-context CP scaling grid,
    joined from ``train_ddp_cp_s{S}_sp{P}_{impl}.json`` artifacts.

    Footprint-capped cells carry their boundary artifact's skip reason in
    place of a throughput (absence stays visible, not silent) — the
    capped Ulysses cells at long S are themselves the finding: dense
    per-head attention's S^2 score footprint is what ring's blockwise
    recurrence removes.
    """
    results_dir = Path(results_dir)
    cells: dict[tuple[int, int], dict[str, Any]] = {}
    for f in sorted(results_dir.glob("train_ddp_cp_s*.json")):
        try:
            r = json.loads(f.read_text())
        except Exception:  # noqa: BLE001 — per-file resilience
            continue
        name = r.get("experiment", {}).get("name", "")
        try:
            _, s_tag, sp_tag, impl = name.split("_")
            seq, sp = int(s_tag[1:]), int(sp_tag[2:])
        except ValueError:
            continue
        cell = cells.setdefault((seq, sp), {})
        status = r.get("status", "")
        est = r.get("estimated_bytes")
        tps = r.get("tokens_per_second")
        if status == "skipped_estimated_footprint" and est is not None:
            cell[impl] = f"skip ({est / 2**30:.0f} GiB est.)"
        elif status.startswith("skipped_"):
            cell[impl] = f"skip ({status.removeprefix('skipped_')})"
        elif status:  # any other boundary artifact (e.g. "infeasible")
            cell[impl] = f"skip ({status})"
        elif tps is None:  # schema-divergent artifact: visible, not fatal
            cell[impl] = "skip (unreadable artifact)"
        else:
            cell[impl] = round(tps, 1)

    def measured(x: Any) -> bool:
        # skip cells are strings; measured throughputs may deserialize
        # as int or float
        return isinstance(x, (int, float)) and not isinstance(x, bool)

    rows: list[dict[str, Any]] = []
    for (seq, sp), cell in sorted(cells.items()):
        ring, uly = cell.get("ring"), cell.get("ulysses")
        both = measured(ring) and measured(uly)
        winner = None
        if both:
            # exact ties get an explicit marker instead of silently
            # crediting ring (the >= would otherwise label them ring wins)
            if ring == uly:
                winner = "tie"
            else:
                winner = "ring" if ring > uly else "ulysses"
        elif measured(ring):
            winner = "ring (ulysses capped)"
        elif measured(uly):
            winner = "ulysses (ring capped)"
        rows.append({
            "seq_len": seq,
            "sp": sp,
            "ring_tokens_per_second": ring,
            "ulysses_tokens_per_second": uly,
            "winner": winner,
            "ring_over_ulysses": round(ring / uly, 4) if both else None,
        })
    return rows


def write_cp_scaling_report(
    results_dir: Path, out_dir: Path
) -> list[dict[str, Any]]:
    """Emit ``cp_scaling.csv`` + ``CP_SCALING.md``; returns the rows."""
    rows = collect_cp_scaling_rows(results_dir)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    with (out_dir / "cp_scaling.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=CP_COLUMNS)
        w.writeheader()
        for r in rows:
            w.writerow(r)

    md = [
        "# Long-context scaling: ring vs Ulysses context parallelism",
        "",
        "Train-step throughput (tokens/s) across the sequence axis at "
        "B=1 on a deliberately tiny model (h=64, 1 layer, 8 heads — the "
        "single-core host prices bigger models out of the S=32768 rows; "
        "both impls share the model, so the ordering survives), sp "
        "degrees {2,4,8} on the simulated mesh "
        "(`results/parallelism/cp_scaling/`"
        " artifacts; producer: `scripts/publish_baselines.py --stage "
        "cp_scaling`).  The reference's \"long context\" axis is payload "
        "bytes only (SURVEY §5.7) — it has no context parallelism; this "
        "grid measures the capability extension.",
        "",
        "Simulated-mesh caveat as everywhere in this corpus: host-core "
        "times, relative ordering is the signal.  `skip (N GiB est.)` "
        "cells are footprint-capped by the publisher (dense per-head "
        "score tensors exceed the host budget) — the capped Ulysses "
        "column at long S is itself the result: ring's blockwise "
        "recurrence keeps only an [S/P, S/P] tile resident where "
        "Ulysses materialises full [S, S] scores per local head.  "
        "`skip (estimated_time)` cells are wall-clock-capped: ring's "
        "total attention compute is Θ(S²) independent of sp "
        "on a serially-simulated mesh.  The measured S axis therefore "
        "ends at S=16384 (all sp degrees); S=32768 is "
        "boundary-documented only — the one budget-admitted cell "
        "(ring sp=8) is the XLA:CPU rendezvous-timeout `infeasible` "
        "cell recorded in its own artifact, and every Ulysses S=32768 "
        "cell is footprint-capped.",
        "",
    ]
    from dlbb_tpu.stats.compare import md_table

    md += md_table(rows, CP_COLUMNS)
    md.append("")
    (out_dir / "CP_SCALING.md").write_text("\n".join(md))
    return rows


# ---------------------------------------------------------------------------
# autotuner agreement report
# ---------------------------------------------------------------------------

AUTOTUNE_COLUMNS = [
    "plan", "role", "predicted_us", "predicted_rank", "measured_rank",
    "goodput_tokens_per_s", "tokens_per_second", "ttft_p50_s",
]


def write_autotune_report(bench_path: "str | Path",
                          out_dir: "str | Path") -> list[dict[str, Any]]:
    """Consolidate ``BENCH_autotune.json`` into ``AUTOTUNE.md`` — the
    model-picked vs measured-winner agreement tables for the plan
    autotuner (``cli plan --auto``, docs/autotune.md).  Returns the
    measured rows (empty when the bench artifact has none — callers
    skip, never clobber)."""
    bench_path = Path(bench_path)
    try:
        bench = json.loads(bench_path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    agreement = bench.get("agreement") or {}
    rows = agreement.get("rows") or []
    if not rows:
        return []
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    tier = bench.get("tier") or {}
    pruned = bench.get("pruned") or {}
    ranked = bench.get("ranked") or []
    md = [
        "# Plan autotuner: model-picked vs measured winner",
        "",
        f"`cli plan --auto` on the {bench.get('devices', '?')}-device "
        f"simulated mesh (target: {bench.get('target', '?')}; "
        f"docs/autotune.md).  The full plan space is enumerated, "
        f"statically pruned (every pruned point journaled with its "
        f"reason — no silent drops), ranked by the fitted cm2 tier "
        f"(`{tier.get('name', '?')}`, fit v"
        f"{(tier.get('fit') or {}).get('fit_version', '?')}), and the "
        f"top-k plus the default-heuristic plan measured through the "
        f"real engines on one shared seeded trace.",
        "",
        "Simulated-mesh caveat as everywhere in this corpus: host-core "
        "times; predicted and measured share the cpu-sim tier, so "
        "relative ordering is the honest signal.  Chip rows stay "
        "`pending_tunnel` in the bench artifact.",
        "",
        "## Search accounting",
        "",
        f"| searched | {' | '.join(pruned)} | ranked | measured |",
        "|---|" + "---|" * (len(pruned) + 2),
        f"| {bench.get('searched', 0)} | "
        + " | ".join(str(v) for v in pruned.values())
        + f" | {len(ranked)} | {len(rows)} |",
        "",
        "## Measured agreement (top-k + default heuristic)",
        "",
    ]
    md += md_table_from_rows(rows, AUTOTUNE_COLUMNS)
    winner = agreement.get("measured_winner")
    speedup = bench.get("speedup_vs_default")
    md += [
        "",
        f"Measured winner: **{winner}** (cm2 predicted winner: "
        f"{agreement.get('predicted_winner')}; top-2 contains measured "
        f"winner: {agreement.get('top2_contains')})."
        + (f"  Speedup vs default heuristic "
           f"`{bench.get('default_plan')}`: **{speedup:.2f}x**."
           if speedup else ""),
        "",
    ]
    cal = bench.get("calibration_agreement") or {}
    fams = [f for f in cal.get("families", [])
            if f.get("status") == "ok"]
    if fams:
        md += [
            "## Calibration-grid agreement (pinned regression)",
            "",
            f"cm2 top-2 contains the measured winner for "
            f"**{cal.get('agree')}/{cal.get('total')}** families "
            f"(ratio {cal.get('ratio'):.2f}; gate >= 0.70, "
            f"`tests/test_autotune.py`) over the committed calibration "
            f"baseline `{cal.get('baseline')}`.",
            "",
            "| family | predicted order (best first) | measured winner "
            "| top-2 contains |",
            "|---|---|---|---|",
        ]
        for f in fams:
            order = " > ".join(
                m.split("::")[-1] for m in f["predicted_order"])
            md.append(
                f"| {f['family']} | {order} | "
                f"{f['measured_winner'].split('::')[-1]} | "
                f"{'yes' if f['top2_contains_winner'] else 'NO'} |")
        missing = [f for f in cal.get("families", [])
                   if f.get("status") == "missing-target"]
        for f in missing:
            md.append(f"| {f['family']} | missing targets: "
                      f"{', '.join(f['missing'])} | — | excluded |")
        md.append("")
    (out / "AUTOTUNE.md").write_text("\n".join(md))
    return rows


def md_table_from_rows(rows: list[dict[str, Any]],
                       columns: list[str]) -> list[str]:
    """Markdown table over whichever of ``columns`` the rows carry
    (serving and train measured rows share a table shape but not every
    metric column)."""
    cols = [c for c in columns
            if any(r.get(c) is not None for r in rows)]
    lines = ["| " + " | ".join(cols) + " |",
             "|---|" + "---|" * (len(cols) - 1)]
    for r in rows:
        cells = []
        for c in cols:
            v = r.get(c)
            if isinstance(v, float):
                v = f"{v:.3f}" if abs(v) < 100 else f"{v:.1f}"
            cells.append("-" if v is None else str(v))
        lines.append("| " + " | ".join(cells) + " |")
    return lines
