"""3D tensor-benchmark statistics.

Schema parity with reference ``collectives/3d/stats.py``: ms-scale stats
(mean/median/min/max only, :32-49), a standard CSV (one row per config,
columns :151-164) and a transposed CSV (metrics as rows, config-id columns
``op_rX_hX_sX_bX``, metadata block appended, :187-282), both sorted
operation → ranks → hidden_dim → seq_len → batch (:167-173).

The standard CSV's columns are the judged artifact contract and stay
byte-identical to the reference's; the ``timing_granularity`` honesty
marker ("per_iteration" vs "chunked(N)" — see ``stats1d``) therefore goes
into the transposed CSV's metadata block instead.
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Any

import numpy as np

STANDARD_COLUMNS = [
    "implementation",
    "operation",
    "num_ranks",
    "hidden_dim",
    "seq_len",
    "batch",
    "tensor_size_mb",
    "num_elements",
    "mean_time_ms",
    "median_time_ms",
    "min_time_ms",
    "max_time_ms",
]

METRICS = ["mean_time_ms", "median_time_ms", "min_time_ms", "max_time_ms"]

_SORT_KEY = lambda r: (  # noqa: E731
    r["operation"], r["num_ranks"], r["hidden_dim"], r["seq_len"], r["batch"],
)


def calculate_statistics_3d(timings_2d: list[list[float]]) -> dict[str, float]:
    """ms-scale aggregate stats (reference ``collectives/3d/stats.py:32-49``).

    Hot loop of the 3D pipeline (hundreds of files per corpus pass) —
    delegates to ``utils.metrics.summarize``, the ONE
    native-C++-with-numpy-fallback summary dispatch (numerics asserted
    identical in ``tests/test_native.py``), and maps its seconds-scale
    fields to the reference's ms keys."""
    from dlbb_tpu.utils.metrics import summarize

    flat = np.asarray(timings_2d, dtype=np.float64).ravel()
    s = summarize(flat)
    return {
        "mean_time_ms": s["mean"] * 1e3,
        "median_time_ms": s["median"] * 1e3,
        "min_time_ms": s["min"] * 1e3,
        "max_time_ms": s["max"] * 1e3,
    }


def process_3d_results(
    input_dir: str | Path,
    output_dir: str | Path,
    implementation: str = "xla_tpu",
    verbose: bool = True,
) -> list[dict[str, Any]]:
    """Process 3D result JSONs → standard + transposed CSVs + summary JSON.

    ``implementation`` names the output files, replacing the reference's
    edit-the-constant switch (``collectives/3d/stats.py:17``).
    """
    input_dir, output_dir = Path(input_dir), Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)

    results: list[dict[str, Any]] = []
    for json_file in sorted(input_dir.glob("*.json")):
        if json_file.name.endswith("_stats.json"):
            continue
        try:
            with open(json_file) as f:
                data = json.load(f)
            shape = data["tensor_shape"]
            results.append(
                {
                    "implementation": data.get("implementation")
                    or data.get("mpi_implementation")
                    or implementation,
                    "operation": data["operation"],
                    "num_ranks": data["num_ranks"],
                    "hidden_dim": shape["hidden_dim"],
                    "seq_len": shape["seq_len"],
                    "batch": shape["batch"],
                    "tensor_size_mb": data["tensor_size_mb"],
                    "num_elements": data["num_elements"],
                    "timing_granularity": data.get(
                        "timing_granularity", "per_iteration"
                    ),
                    **calculate_statistics_3d(data["timings"]),
                }
            )
        except Exception as e:  # noqa: BLE001 — per-file resilience
            if verbose:
                print(f"  ERROR processing {json_file.name}: {e}")
            continue

    if not results:
        return results
    results.sort(key=_SORT_KEY)

    std_path = output_dir / f"benchmark_statistics_3d_{implementation}_standard.csv"
    with open(std_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=STANDARD_COLUMNS)
        writer.writeheader()
        for r in results:
            writer.writerow({k: r[k] for k in STANDARD_COLUMNS})

    tr_path = output_dir / f"benchmark_statistics_3d_{implementation}_transpose.csv"
    config_ids = [
        f"{r['operation']}_r{r['num_ranks']}_h{r['hidden_dim']}"
        f"_s{r['seq_len']}_b{r['batch']}"
        for r in results
    ]
    with open(tr_path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["Metric"] + config_ids)
        for metric in METRICS:
            writer.writerow([metric] + [r[metric] for r in results])
        writer.writerow([])
        writer.writerow(["--- Metadata ---"])
        for meta in (
            "operation", "num_ranks", "hidden_dim", "seq_len", "batch",
            "tensor_size_mb", "timing_granularity",
        ):
            writer.writerow([meta] + [r[meta] for r in results])

    if verbose:
        print(f"Standard CSV saved: {std_path}")
        print(f"Transposed CSV saved: {tr_path}")
    return results
