"""The north-star curve, rendered as one committed table.

The driver metric (BASELINE.json) is "all-reduce bus bandwidth (GB/s) +
p50 latency vs msg size, 1 KB–1 GB, fp32+bf16".  The measurements live
scattered across the per-config 1D stats; this module collapses them into
a single per-op table — rows = size labels in payload order, one column
group per (ranks, dtype) — so the literal metric is readable in one
place (``stats/northstar/NORTHSTAR.md`` + per-op CSVs).

Cells show ``median_time_us / bandwidth_gbps`` from the same stats rows
the comparison report consumes (median = the metric's p50; bandwidth =
the reference's uniform formula, ``stats1d.calculate_bandwidth``).
Absent cells are honest absences (memory-capped configs — the committed
skip log in the publisher is their artifact).
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Any

NORTH_STAR_OPS = ("allreduce", "allgather", "broadcast")

_DTYPE_SHORT = {"bfloat16": "bf16", "float32": "fp32", "float16": "fp16"}


def _read_stats_csv(csv_path: Path) -> list[dict[str, Any]]:
    with Path(csv_path).open() as f:
        return list(csv.DictReader(f))


def build_curve(
    rows: list[dict[str, Any]], operation: str
) -> tuple[list[str], list[dict[str, Any]], list[tuple[int, str]]]:
    """(size labels in payload order, table rows, (ranks, dtype) column
    keys) for one op."""
    from dlbb_tpu.stats.variants_report import _parse_size_label

    sub = [r for r in rows if r["operation"] == operation]
    sizes = sorted(
        {r["data_size_name"] for r in sub},
        key=lambda s: (_parse_size_label(s), s),
    )
    cols = sorted({
        (int(r["num_ranks"]), r.get("dtype") or "bfloat16") for r in sub
    })
    cells = {
        (r["data_size_name"], int(r["num_ranks"]),
         r.get("dtype") or "bfloat16"): r
        for r in sub
    }
    table = []
    for size in sizes:
        row: dict[str, Any] = {"size": size}
        for ranks, dtype in cols:
            r = cells.get((size, ranks, dtype))
            key = f"{ranks}r/{_DTYPE_SHORT.get(dtype, dtype)}"
            if r is None:
                row[key] = None
                continue
            med = float(r["median_time_us"])
            bw = r.get("bandwidth_gbps")
            bw_s = f"{float(bw):.3g}" if bw not in (None, "") else "?"
            row[key] = f"{med:,.0f}us / {bw_s}GB/s"
        table.append(row)
    col_names = [f"{n}r/{_DTYPE_SHORT.get(d, d)}" for n, d in cols]
    return sizes, table, col_names  # type: ignore[return-value]


def default_stats_1d_csv(stats_root: Path) -> Path:
    """The consolidated 1D stats CSV under a stats tree — single source of
    the path for the publisher stage and the ``reports`` CLI."""
    return Path(stats_root) / "1d" / "xla_tpu" / "benchmark_statistics.csv"


def write_northstar_report(
    stats_1d_csv: Path,
    out_dir: Path,
    operations: tuple[str, ...] = NORTH_STAR_OPS,
) -> dict[str, int]:
    """Emit ``NORTHSTAR.md`` + per-op ``northstar_<op>.csv``; returns
    {op: row count}.  No-op (returns {}, writes nothing) when the stats
    CSV is absent or holds no north-star op rows — a partial regeneration
    must never clobber the committed report with an empty shell."""
    stats_1d_csv = Path(stats_1d_csv)
    if not stats_1d_csv.exists():
        return {}
    rows = _read_stats_csv(stats_1d_csv)
    curves = {}
    for op in operations:
        sizes, table, col_names = build_curve(rows, op)
        if table:
            curves[op] = (table, col_names)
    if not curves:
        return {}
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    from dlbb_tpu.stats.compare import md_table

    md = [
        "# North-star curve — p50 latency / bus bandwidth vs message size",
        "",
        "The driver metric (`BASELINE.json`): all-reduce bus bandwidth + "
        "p50 latency vs msg size, 1 KB–1 GB, fp32+bf16 — plus the "
        "allgather/broadcast companions of configs[1].  One column per "
        "(rank count, dtype); cells are `median_us / bandwidth_GB/s` from "
        "the committed per-config stats (`stats/1d/xla_tpu`).  Size "
        "labels are the reference's (nominal — byte counts in the "
        "artifacts); blank cells are memory-capped configs whose skip is "
        "logged by the publisher.  All values are the CPU-simulated mesh "
        "(host-RAM collectives, not ICI — see COMPARISON.md caveats); "
        "note bf16 is software-emulated on the host CPU, which is why "
        "fp32 columns often beat bf16 here — on TPU hardware that "
        "relationship inverts (bf16 is the native MXU type).",
        "",
    ]
    counts: dict[str, int] = {}
    for op, (table, col_names) in curves.items():
        counts[op] = len(table)
        columns = ["size", *col_names]
        with (out_dir / f"northstar_{op}.csv").open(
            "w", newline=""
        ) as f:
            w = csv.DictWriter(f, fieldnames=columns)
            w.writeheader()
            w.writerows(table)
        md += [f"## {op}", ""]
        md += md_table(table, columns)
        md.append("")
    (out_dir / "NORTHSTAR.md").write_text("\n".join(md))
    return counts
