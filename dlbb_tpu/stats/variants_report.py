"""Variant tuning comparison — which mesh/reduction variant wins.

The reference's tuning story is encoded in its result directories: 8
``CCL_ALLREDUCE`` algorithms x worker counts x fusion toggles, each a
``dsccl_*`` corpus dir whose stats answer "which algorithm is fastest at
which size" (SURVEY §2.3; e.g. ``collectives/3d/stats/dscclworker4/``).
This module is the dlbb_tpu capstone of that axis: it joins the committed
``stats/variants/<impl>/benchmark_statistics.csv`` files (produced by the
publisher's variants stage over the executable variant matrix) into one
per-size comparison table with the winning variant per row, emitted as a
committed CSV + markdown report.

Comparison is at the largest rank count every variant could execute
(fixed-shape variants like ``grid2x2x2`` only run at their mesh size — 8);
the join drops variants missing a row rather than guessing.
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Any, Optional


def _read_rows(csv_path: Path) -> list[dict[str, Any]]:
    with csv_path.open() as f:
        return list(csv.DictReader(f))


def collect_variant_rows(
    variants_stats_root: Path,
    operation: str = "allreduce",
    num_ranks: int = 8,
) -> tuple[dict[str, dict[str, float]], dict[str, int]]:
    """``({impl: {data_size_name: mean_time_us}}, {data_size_name:
    num_elements})`` for one (op, ranks).  Empty dicts when the stats root
    does not exist yet (fresh tree)."""
    out: dict[str, dict[str, float]] = {}
    size_elems: dict[str, int] = {}
    root = Path(variants_stats_root)
    if not root.is_dir():
        return out, size_elems
    for impl_dir in sorted(root.iterdir()):
        stats_csv = impl_dir / "benchmark_statistics.csv"
        if not impl_dir.is_dir() or not stats_csv.exists():
            continue
        rows: dict[str, float] = {}
        for r in _read_rows(stats_csv):
            if (r["operation"] != operation
                    or int(r["num_ranks"]) != num_ranks):
                continue
            size = r["data_size_name"]
            rows[size] = float(r["mean_time_us"])
            if r.get("num_elements"):
                size_elems[size] = int(r["num_elements"])
        if rows:
            out[impl_dir.name] = rows
    return out, size_elems


def _parse_size_label(label: str) -> int:
    """'64KB' -> 65536, '1GB' -> 2**30; unparseable labels sort first."""
    import re

    m = re.fullmatch(r"(\d+)(KB|MB|GB)", label.strip())
    if not m:
        return 0
    return int(m.group(1)) * {"KB": 2**10, "MB": 2**20, "GB": 2**30}[m.group(2)]


def _build_table(
    data: dict[str, dict[str, float]],
    size_elems: dict[str, int],
    baseline_impl: str,
) -> tuple[list[dict[str, Any]], dict[str, dict[str, Any]], list[str],
           list[str]]:
    """(table rows, per-size winners, sizes, impls) for one rank count."""
    impls = sorted(data)
    all_sizes = {s for rows in data.values() for s in rows}
    # payload size is the true row order; num_elements comes from the same
    # stats CSVs, with the size label parsed as fallback (reference-schema
    # CSVs lack the column) and the name as final tiebreaker so the
    # committed row order never depends on set-iteration order
    sizes = sorted(
        all_sizes,
        key=lambda s: (size_elems.get(s, _parse_size_label(s)), s),
    )
    table: list[dict[str, Any]] = []
    winners: dict[str, dict[str, Any]] = {}
    for size in sizes:
        row: dict[str, Any] = {"data_size_name": size}
        present = {
            impl: rows[size] for impl, rows in data.items() if size in rows
        }
        for impl in impls:
            row[impl] = round(present[impl], 3) if impl in present else None
        winner = min(present, key=present.get)  # type: ignore[arg-type]
        row["winner"] = winner
        base = present.get(baseline_impl)
        speedup = (
            round(base / present[winner], 4)
            if base is not None and present[winner] > 0 else None
        )
        row["winner_speedup_vs_default"] = speedup
        winners[size] = {
            "winner": winner,
            "mean_time_us": round(present[winner], 3),
            "speedup_vs_default": speedup,
        }
        table.append(row)
    return table, winners, sizes, impls


def write_variants_report(
    variants_stats_root: Path,
    out_dir: Optional[Path] = None,
    operation: str = "allreduce",
    rank_counts: tuple[int, ...] = (2, 4, 8, 16),
    primary_ranks: int = 8,
    baseline_impl: str = "xla_tpu",
) -> dict[str, Any]:
    """Emit ``variants_comparison.csv`` (the ``primary_ranks`` table) +
    per-rank ``variants_comparison_ranks{N}.csv`` + one ``VARIANTS.md``
    with a section per rank count that has data; returns the summary —
    the primary table's per-size winners at the top level (legacy shape)
    plus every rank count's winners under ``"ranks"``."""
    out_dir = Path(out_dir) if out_dir is not None else Path(variants_stats_root)
    per_rank: dict[int, tuple] = {}
    for n in rank_counts:
        data, size_elems = collect_variant_rows(
            variants_stats_root, operation, n
        )
        if data:
            per_rank[n] = _build_table(data, size_elems, baseline_impl)
    if not per_rank:
        return {"sizes": [], "winners": {}}
    # the rank count the legacy top-level summary (and the legacy
    # variants_comparison.csv filename) actually describes: the requested
    # primary when it has data, else the largest measured rank count —
    # recorded in the summary so a substitution is never silent
    primary_n = (primary_ranks if primary_ranks in per_rank
                 else max(per_rank))

    out_dir.mkdir(parents=True, exist_ok=True)
    md = [
        f"# Variant tuning comparison — {operation}",
        "",
        "Per-size mean time (µs) across the executable tuning variants "
        "(`dlbb_tpu/comm/variants.py`) — the analogue of the reference's "
        "`CCL_ALLREDUCE` algorithm sweep corpus (SURVEY §2.3), one "
        "section per measured rank count.  "
        f"`winner_speedup_vs_default` is {baseline_impl} mean / winner "
        "mean (>1: tuning beats the default).  Blank cells: that variant "
        "has no row at this size (fixed-shape meshes only run at their "
        "own rank count; memory-capped configs are skipped).",
        "",
    ]
    for n, (table, _, _, impls) in sorted(per_rank.items()):
        columns = ["data_size_name", *impls, "winner",
                   "winner_speedup_vs_default"]
        csv_name = ("variants_comparison.csv" if n == primary_n
                    else f"variants_comparison_ranks{n}.csv")
        with (out_dir / csv_name).open("w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=columns)
            w.writeheader()
            w.writerows(table)
        from dlbb_tpu.stats.compare import md_table

        md += [f"## {n} ranks", ""]
        md += md_table(table, columns)
        md.append("")
    (out_dir / "VARIANTS.md").write_text("\n".join(md))

    _, winners, sizes, _ = per_rank[primary_n]
    return {
        "sizes": sizes,
        "winners": winners,
        "primary_rank_count": primary_n,
        "ranks": {
            n: {"sizes": s, "winners": w}
            for n, (_, w, s, _) in sorted(per_rank.items())
        },
    }


def write_variants3d_report(
    variants3d_stats_root: Path,
    base_3d_stats_csv: Optional[Path] = None,
    out_dir: Optional[Path] = None,
    operation: str = "allreduce",
) -> list[dict[str, Any]]:
    """3D-shape comparison of the tuned variants against the default
    corpus — the reference tuned its CCL algorithms on the 3D LLM-shaped
    sweep (``collectives/3d/launch_dsccl.sh``), so the 1D winners get the
    same treatment.  Joins each ``stats/variants3d/<impl>/...standard.csv``
    with the default 3D stats per (op, ranks, batch, seq, hidden); emits
    ``VARIANTS3D.md`` + ``variants3d_comparison.csv``; returns the rows.

    ``base_3d_stats_csv`` defaults to the sibling default-corpus stats
    (``<stats root>/3d/xla_tpu/...standard.csv``) so the artifact producer
    and the ``reports`` CLI cannot drift on the path; ``out_dir`` defaults
    to the variants3d root itself."""
    root = Path(variants3d_stats_root)
    if base_3d_stats_csv is None:
        base_3d_stats_csv = (
            root.parent / "3d" / "xla_tpu"
            / "benchmark_statistics_3d_xla_tpu_standard.csv"
        )
    if out_dir is None:
        out_dir = root
    impls: dict[str, dict[tuple, float]] = {}

    def read_standard(csv_path: Path, impl: str) -> dict[tuple, float]:
        out: dict[tuple, float] = {}
        with csv_path.open() as f:
            for r in csv.DictReader(f):
                # filter on the implementation column too: a combined CSV
                # must not silently merge other impls under this name
                if (r["operation"] != operation
                        or r.get("implementation", impl) != impl):
                    continue
                key = (int(r["num_ranks"]), int(r["batch"]),
                       int(r["seq_len"]), int(r["hidden_dim"]))
                out[key] = float(r["mean_time_ms"])
        return out

    base_3d_stats_csv = Path(base_3d_stats_csv)
    if base_3d_stats_csv.exists():
        impls["xla_tpu"] = read_standard(base_3d_stats_csv, "xla_tpu")
    if root.is_dir():
        for impl_dir in sorted(root.iterdir()):
            std = sorted(impl_dir.glob("*_standard.csv"))
            if not impl_dir.is_dir() or not std:
                continue
            if len(std) > 1:
                raise ValueError(
                    f"{impl_dir} holds {len(std)} *_standard.csv files — "
                    "ambiguous input; remove the stale one"
                )
            if impl_dir.name in impls:
                # a dir named "xla_tpu" would silently shadow the
                # default-corpus baseline — same ambiguity class as the
                # duplicate-CSV check above
                raise ValueError(
                    f"{impl_dir} would shadow the already-loaded "
                    f"{impl_dir.name!r} corpus (baseline comes from "
                    f"{base_3d_stats_csv})"
                )
            impls[impl_dir.name] = read_standard(std[0], impl_dir.name)
    if not impls:
        return []

    names = sorted(impls)
    keys = sorted(set().union(*[set(v) for v in impls.values()]))
    rows: list[dict[str, Any]] = []
    for key in keys:
        present = {n: impls[n][key] for n in names if key in impls[n]}
        if len(present) < 2:
            continue  # a comparison needs at least two columns
        row: dict[str, Any] = {
            "num_ranks": key[0], "batch": key[1], "seq_len": key[2],
            "hidden_dim": key[3],
        }
        for n in names:
            row[n] = round(present[n], 4) if n in present else None
        winner = min(present, key=present.get)  # type: ignore[arg-type]
        row["winner"] = winner
        base = present.get("xla_tpu")
        row["winner_speedup_vs_default"] = (
            round(base / present[winner], 4)
            if base is not None and present[winner] > 0 else None
        )
        rows.append(row)

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    columns = ["num_ranks", "batch", "seq_len", "hidden_dim", *names,
               "winner", "winner_speedup_vs_default"]
    with (out_dir / "variants3d_comparison.csv").open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=columns)
        w.writeheader()
        w.writerows(rows)
    from dlbb_tpu.stats.compare import md_table

    wins = {n: sum(1 for r in rows if r["winner"] == n) for n in names}
    md = [
        f"# 3D-shape variant comparison — {operation} "
        "(mean ms per config)",
        "",
        "The tuned variants measured on the reference's 3D LLM-shaped "
        "sweep, against the default-variant corpus "
        "(`results/3d/xla_tpu`) — the analogue of the reference tuning "
        "its CCL algorithms on the 3D shape "
        "(`collectives/3d/launch_dsccl.sh`).  The two 1D winners (ring, "
        "grid4x2) cover the FULL 3D grid; every other executable "
        "variant covers the reference's reduced tuning grid — "
        "allreduce, B {8,16} x S {2048,4096} x H {2048,4096}, ranks "
        "{4,8} (`collectives/3d/dsccl.py:20-28`; 8-rank mesh shapes "
        "rank-gate to the 8-rank rows) — via the `variants3d_tuning` "
        "publisher stage.  Blank cells are outside a variant's grid or "
        "memory-capped (logged skips).  Wins per variant: "
        + ", ".join(f"{n}: {wins[n]}" for n in names) + ".",
        "",
    ]
    md += md_table(rows, columns)
    md.append("")
    (out_dir / "VARIANTS3D.md").write_text("\n".join(md))
    return rows
