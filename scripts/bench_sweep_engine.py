#!/usr/bin/env python
"""Reproducible before/after evidence for the pipelined sweep engine.

Runs the same fixed mini-grid (2 ops x 2 sizes x 2 rank counts on the
8-device CPU-simulated mesh) through four engine settings — serial vs
pipelined, each cold-cache then warm-cache — and writes the wall-clock /
compile-time comparison to ``BENCH_sweep.json`` at the repo root.  The
perf claim the artifact pins: warm-cache sweeps (either mode) finish in
measurably less wall time than the cold serial sweep, while the measured
medians stay statistically equivalent across modes.

Usage: python scripts/bench_sweep_engine.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

force_cpu_simulation(8)

from dlbb_tpu.bench.runner import Sweep1D, run_sweep  # noqa: E402
from dlbb_tpu.bench.schedule import MANIFEST_NAME  # noqa: E402

# The fixed micro-grid: 2 ops x 2 sizes x 2 rank counts.  Small payloads
# on purpose: the engine's win is COMPILE amortisation, so the harness
# keeps per-config measurement cost small relative to per-config compile
# cost — the regime the full publisher grids (~100 configs, most of them
# sub-second to measure on this host, each paying a fresh trace+compile
# on a --fresh re-run) actually live in.  At GiB labels measurement
# dominates wall time and any compile win drowns (measured: ~0.3s
# compile in a ~12s sweep on the 16MB grid).
GRID = dict(
    operations=("allreduce", "allgather"),
    data_sizes=(("1KB", 256), ("64KB", 16384)),
    rank_counts=(2, 4),
)


def _one_run(name: str, work: Path, cache: Path, pipeline: bool,
             iters: int) -> dict:
    out = work / name
    sweep = Sweep1D(
        implementation="bench_sweep",
        dtype="float32",
        warmup_iterations=2,
        measurement_iterations=iters,
        output_dir=str(out),
        compile_cache=str(cache),
        pipeline=pipeline,
        **GRID,
    )
    t0 = time.perf_counter()
    files = run_sweep(sweep, verbose=False)
    wall = time.perf_counter() - t0
    manifest = json.loads((out / MANIFEST_NAME).read_text())
    medians = {}
    for f in files:
        d = json.loads(Path(f).read_text())
        flat = [t for row in d["timings"] for t in row]
        flat.sort()
        key = f"{d['operation']}_r{d['num_ranks']}_{d['data_size_name']}"
        medians[key] = flat[len(flat) // 2]
    return {
        "pipeline": pipeline,
        "wall_seconds": round(wall, 4),
        "compile_seconds_total": round(
            manifest["compile_seconds_total"], 4),
        "persistent_cache_hits":
            manifest["compile_cache"]["persistent_hits"],
        "persistent_cache_misses":
            manifest["compile_cache"]["persistent_misses"],
        "payload_cache_hits": manifest["payload_cache"]["hits"],
        "artifacts": len(files),
        "median_seconds_per_config": medians,
    }


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _aggregate(reps: list[dict]) -> dict:
    """Per-setting aggregate over interleaved repetitions: median wall
    (with min/max as the honest spread) and per-config medians of the
    per-rep medians."""
    walls = [r["wall_seconds"] for r in reps]
    keys = reps[0]["median_seconds_per_config"]
    return {
        "pipeline": reps[0]["pipeline"],
        "repetitions": len(reps),
        "wall_seconds_median": round(_median(walls), 4),
        "wall_seconds_min": round(min(walls), 4),
        "wall_seconds_max": round(max(walls), 4),
        "compile_seconds_total_median": round(_median(
            [r["compile_seconds_total"] for r in reps]), 4),
        "persistent_cache_hits": reps[-1]["persistent_cache_hits"],
        "persistent_cache_misses": reps[-1]["persistent_cache_misses"],
        "payload_cache_hits": reps[-1]["payload_cache_hits"],
        "artifacts": reps[-1]["artifacts"],
        "median_seconds_per_config": {
            k: _median([r["median_seconds_per_config"][k] for r in reps])
            for k in keys
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30,
                    help="measured iterations per config (default 30)")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per setting (default 3; "
                         "run-to-run medians on an oversubscribed host "
                         "swing several-fold, so single runs mislead)")
    ap.add_argument("--output", default=str(REPO / "BENCH_sweep.json"))
    args = ap.parse_args()

    work = Path(tempfile.mkdtemp(prefix="bench_sweep_"))
    warm_cache = work / "cache_warm"
    reps: dict[str, list[dict]] = {
        "serial_cold": [], "pipelined_cold": [],
        "serial_warm": [], "pipelined_warm": [],
    }
    try:
        # warms the shared cache for the *_warm settings AND absorbs
        # process-level one-time costs (imports, first dispatch) so they
        # don't bias the first measured setting
        _one_run("warmup", work, warm_cache, True, 3)

        # interleave settings within each repetition so host drift
        # (the 2-core box runs other work) cancels across modes
        for rep in range(args.reps):
            for name, pipeline, cache in (
                ("serial_cold", False, work / f"cache_sc{rep}"),
                ("pipelined_cold", True, work / f"cache_pc{rep}"),
                ("serial_warm", False, warm_cache),
                ("pipelined_warm", True, warm_cache),
            ):
                reps[name].append(_one_run(
                    f"{name}_{rep}", work, cache, pipeline, args.iters))
    finally:
        shutil.rmtree(work, ignore_errors=True)

    runs = {name: _aggregate(r) for name, r in reps.items()}
    cold = runs["serial_cold"]["wall_seconds_median"]
    summary = {
        "speedup_vs_serial_cold": {
            name: round(cold / r["wall_seconds_median"], 3)
            for name, r in runs.items()
        },
        # the headline claim: a warm persistent cache beats the cold
        # serial baseline, and beats its own mode's cold run too
        "warm_below_cold_serial":
            runs["serial_warm"]["wall_seconds_median"] < cold,
        "warm_below_cold_per_mode": {
            mode: (runs[f"{mode}_warm"]["wall_seconds_median"]
                   < runs[f"{mode}_cold"]["wall_seconds_median"])
            for mode in ("serial", "pipelined")
        },
    }
    # cross-mode timing equivalence, with the same-mode noise floor it
    # must be judged against: per-config ratio of (median across reps)
    # medians, pipelined/serial, plus the serial run-to-run spread
    ratios = []
    for key, ms in runs["serial_cold"]["median_seconds_per_config"].items():
        mp = runs["pipelined_cold"]["median_seconds_per_config"][key]
        ratios.append(mp / ms)
    summary["pipelined_vs_serial_median_ratio_p50"] = round(
        _median(ratios), 3)
    spreads = []
    for key in reps["serial_cold"][0]["median_seconds_per_config"]:
        vals = [r["median_seconds_per_config"][key]
                for r in reps["serial_cold"]]
        spreads.append(max(vals) / max(min(vals), 1e-12))
    summary["serial_run_to_run_spread_p50"] = round(_median(spreads), 3)

    import jax

    record = {
        "harness": "scripts/bench_sweep_engine.py",
        "grid": "2 ops x 2 sizes x 2 rank counts, 8-device simulated mesh",
        "iterations_per_config": args.iters,
        "repetitions": args.reps,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "timestamp": time.time(),
        "runs": runs,
        "summary": summary,
    }
    atomic_write_text(json.dumps(record, indent=2) + "\n",
                      Path(args.output))
    print(json.dumps(summary, indent=2))
    print(f"written to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
