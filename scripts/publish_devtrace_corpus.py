"""Publish the committed device-capture corpus + the β-identified cm2
refit (docs/observability.md, "Device-trace analysis").

One command regenerates the whole committed chain:

1. a captured sim-mesh mini-sweep (4 registry collectives x 4 payload
   sizes + the four overlap-proof collective-matmul schedules) into
   ``results/fit_corpus/devtrace/sim8/`` — result JSONs with capture
   metadata, perfetto trace-event JSON + xplane per config;
2. ``obs devtrace`` over it into ``stats/analysis/devtrace/sim8.*`` —
   the per-op measured timelines, measured-vs-static overlap table and
   the op-level fit samples;
3. ``obs fit`` over the full corpus (program-scale artifacts +
   calibration rows + the new device-timed op samples) appending a new
   version to ``stats/analysis/costmodel_fit/cm2_cpu-sim.json`` — the
   version where β is identified from op-granularity device time
   instead of pinned from cm1;
4. ``obs calibrate --model cm2`` against the new fit, committing the
   regenerated ``calibration_baseline_cm2.json`` the
   ``obs diff --model cm2`` CI gate compares against.

Run from the repo root on an OTHERWISE-IDLE host (the same discipline
as the PR-12 baseline regeneration — a loaded host silently loosens
the diff gate):

    JAX_PLATFORMS=cpu python scripts/publish_devtrace_corpus.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

force_cpu_simulation(8)

CORPUS_DIR = Path("results/fit_corpus/devtrace/sim8")
CAPTURE_DIR = CORPUS_DIR / "captures"
DEVTRACE_DIR = Path("stats/analysis/devtrace")

SIZES = (("1KB", 256), ("64KB", 16384), ("1MB", 262144),
         ("16MB", 4194304))
OPS_1D = ("allreduce", "allgather", "reducescatter", "alltoall")
OVERLAP = (("ag_matmul", "overlap_ring"), ("ag_matmul", "overlap_bidir"),
           ("matmul_rs", "overlap_ring"), ("matmul_rs", "overlap_bidir"))


def main() -> int:
    from dlbb_tpu.bench import Sweep1D, Sweep3D, run_sweep
    from dlbb_tpu.obs import run_obs
    from dlbb_tpu.obs.calibration import (
        run_calibration,
        save_calibration_baseline,
    )

    print("[1/4] captured mini-sweep ->", CORPUS_DIR)
    run_sweep(Sweep1D(
        operations=OPS_1D,
        data_sizes=SIZES,
        rank_counts=(8,),
        warmup_iterations=2,
        measurement_iterations=8,
        output_dir=str(CORPUS_DIR),
        pipeline=False,
        compile_cache="off",
        device_trace_dir=str(CAPTURE_DIR),
    ), verbose=False)
    for op, variant in OVERLAP:
        run_sweep(Sweep3D(
            operations=(op,),
            variant=variant,
            batch_sizes=(8,),
            seq_lengths=(64,),
            hidden_dims=(128,),
            rank_counts=(8,),
            warmup_iterations=2,
            measurement_iterations=8,
            output_dir=str(CORPUS_DIR),
            pipeline=False,
            compile_cache="off",
            device_trace_dir=str(CAPTURE_DIR),
        ), verbose=False)

    print("[2/4] obs devtrace ->", DEVTRACE_DIR)
    rc = run_obs("devtrace", journal=str(CORPUS_DIR),
                 output=str(DEVTRACE_DIR))
    if rc != 0:
        print(f"devtrace gate not clean (exit {rc}) — corpus NOT "
              "published")
        return rc

    print("[3/4] obs fit (program corpus + device op samples)")
    rc = run_obs("fit",
                 journal=None, output=None,
                 results=["results/fit_corpus",
                          str(DEVTRACE_DIR / "sim8.json")],
                 tier="cpu-sim", host_filter="calibration")
    if rc != 0:
        print(f"fit refused (exit {rc})")
        return rc

    print("[4/4] obs calibrate --model cm2 -> committed baseline")
    report = run_calibration(out_dir=Path("results/obs"), model="cm2")
    path = save_calibration_baseline(report)
    print("baseline written:", path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
