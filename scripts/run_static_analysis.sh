#!/usr/bin/env bash
# comm-lint CI gate: both static passes, no TPU needed.
#
#   scripts/run_static_analysis.sh [report.json]
#
# Runs the AST source lint over dlbb_tpu/ + scripts/ and the HLO collective
# audit on an 8-device CPU-simulated mesh (the same surface as
# `python -m dlbb_tpu.cli analyze all --simulate 8`), then the fast tier-1
# analyzer tests.  Exit nonzero on any finding or test failure.
set -euo pipefail
cd "$(dirname "$0")/.."

REPORT="${1:-results/analysis/comm_lint.json}"

JAX_PLATFORMS=cpu python -m dlbb_tpu.cli analyze all --simulate 8 \
    --strict-warnings --json "$REPORT"

JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q -m 'not slow' \
    -p no:cacheprovider

# schedule_smoke (docs/schedule_audit.md): the α–β schedule audit runs
# INSIDE `analyze all` above (one lowering serves the byte + schedule
# passes: every ring hop must be hidden behind a straddling matmul, no
# divergent-branch collective sequences).  `analyze diff` re-audits once
# for the regression-baseline gate against the committed
# stats/analysis/baselines/ snapshots (fails on >10% critical-path /
# wire growth or any new collective kind; `analyze snapshot` regenerates
# after an intended change).  Exit-code contract pinned at 0 clean /
# 1 findings / 2 crash so this composes with the chaos and compression
# stages below.
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli analyze diff --simulate 8
JAX_PLATFORMS=cpu python -m pytest tests/test_schedule_audit.py -q \
    -m schedule_smoke -p no:cacheprovider

# memory_smoke (docs/memory_audit.md): the buffer-liveness memory audit
# runs INSIDE `analyze all` above (per-target peak_live_bytes against
# the analytic ceilings, donation aliasing, the transient-replicated
# gate and the serving-cache cross-check), and `analyze diff` above
# regression-gates the committed peak/transient snapshots (>10% growth
# on the memory axis alone fails).  The pytest marker pins the donation
# proof on real serving/train targets AND the seeded violations
# (dropped donation, fat replicated intermediate) exiting 1; the CLI
# run below exercises the observability surface — memory_audit.json +
# sweep_manifest merge + analysis_peak_live_bytes gauges — over the
# default registry, clean with zero suppressions.
JAX_PLATFORMS=cpu python -m pytest tests/test_memory_audit.py -q \
    -m memory_smoke -p no:cacheprovider
MEM_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli analyze memory --simulate 8 \
    --strict-warnings --output "$MEM_TMP"
grep -q 'dlbb_analysis_peak_live_bytes' "$MEM_TMP/metrics.prom" \
    || { echo "memory_smoke: metrics.prom lost the peak gauges"; exit 1; }
grep -q '"memory_audit"' "$MEM_TMP/sweep_manifest.json" \
    || { echo "memory_smoke: manifest lost the memory-audit record"; \
         exit 1; }
rm -rf "$MEM_TMP"

# numerics_smoke (docs/numerics.md): the dtype-flow numerics audit runs
# INSIDE `analyze all` above (low-precision accumulators priced with
# Higham sequential/tree error bounds, silent upcasts against the
# declared policy dtype, quantise round trips without intervening
# arithmetic, convert churn across fusion boundaries, bitwise-
# reproducibility claims vs multi-replica reduction order), and
# `analyze diff` above regression-gates the committed numerics axis
# (>2x error-bound growth, >1.25x convert churn, or ANY new
# low-precision accumulation site fails).  The pytest marker pins the
# seeded-violation fixtures tripping every rule, real targets staying
# clean, and the fp64 shadow cross-check; the CLI run below exercises
# the observability surface — numerics_audit.json + manifest merge +
# analysis_numerics_* and per-pass analysis_findings gauges — over the
# default registry (the pass fails closed on an empty target surface),
# clean with zero suppressions.  The standalone shadow run then
# re-confirms the analytic bounds empirically against fp64 references.
JAX_PLATFORMS=cpu python -m pytest tests/test_numerics_audit.py -q \
    -m numerics_smoke -p no:cacheprovider
NUM_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli analyze numerics --simulate 8 \
    --strict-warnings --output "$NUM_TMP"
grep -q 'dlbb_analysis_numerics_max_rel_error_bound' "$NUM_TMP/metrics.prom" \
    || { echo "numerics_smoke: metrics.prom lost the error-bound gauges"; \
         exit 1; }
grep -q 'dlbb_analysis_findings{' "$NUM_TMP/metrics.prom" \
    || { echo "numerics_smoke: metrics.prom lost the per-pass finding gauges"; \
         exit 1; }
grep -q '"numerics_audit"' "$NUM_TMP/sweep_manifest.json" \
    || { echo "numerics_smoke: manifest lost the numerics-audit record"; \
         exit 1; }
JAX_PLATFORMS=cpu python -m dlbb_tpu.analysis.numerics_shadow \
    --output "$NUM_TMP/shadow"
grep -q '"refuted": 0' "$NUM_TMP/shadow/shadow_report.json" \
    || { echo "numerics_smoke: shadow cross-check refuted a static bound"; \
         exit 1; }
rm -rf "$NUM_TMP"

# obs_smoke (docs/observability.md): a span-traced + device-captured
# mini-sweep must publish stats equivalent to an untraced serial run
# (dedicated profile reps never enter the stats series; the span trace
# is valid Perfetto-loadable trace-event JSON), then the
# predicted-vs-measured calibration loop — `cli obs calibrate` on a
# micro-op subset joined against the committed α–β schedule baselines,
# and `cli obs diff` against the committed sim-tier calibration
# baseline (stats/analysis/calibration/), failing when the cost-model
# error regresses past the slack.  The profiler-in-timed-region lint
# rule gating captures runs in `analyze all` above.  Exit codes pinned
# 0 clean / 1 findings / 2 crash, like every other gate here.
JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q \
    -m obs_smoke -p no:cacheprovider
OBS_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli obs diff --simulate 8 \
    --output "$OBS_TMP" --targets "::allgather" "::alltoall" "::barrier" \
    --reps 15 --warmup 5
rm -rf "$OBS_TMP"

# fit_smoke (docs/observability.md, "Fitting & attribution"): the cm2
# loop — (1) the fit pipeline proves out on the committed mini corpus
# (results/fit_corpus) into a THROWAWAY DB: seeded-coefficient recovery
# + degenerate-corpus refusal run in the pytest marker; (2) `obs
# calibrate --model cm2` prices a micro-op subset from the COMMITTED
# fitted DB (stats/analysis/costmodel_fit/) and `obs diff --model cm2`
# gates the joined-subset geomean against the committed cm2 calibration
# baseline (stats/analysis/calibration/calibration_baseline_cm2.json);
# (3) the calibrate run's sweep_manifest.json must record the fitted-DB
# version it priced with.
JAX_PLATFORMS=cpu python -m pytest tests/test_costmodel_fit.py -q \
    -m fit_smoke -p no:cacheprovider
FIT_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli obs fit \
    --results results/fit_corpus --tier cpu-sim --fit-dir "$FIT_TMP/db"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli obs diff --model cm2 --simulate 8 \
    --output "$FIT_TMP/cal" --targets "::allgather" "::alltoall" \
    "::barrier" --reps 15 --warmup 5
grep -q '"fit_version"' "$FIT_TMP/cal/sweep_manifest.json" \
    || { echo "fit_smoke: calibrate manifest lost the fitted-DB version"; \
         exit 1; }
rm -rf "$FIT_TMP"

# devtrace_smoke (docs/observability.md, "Device-trace analysis"): the
# captured pipeline end-to-end — the pytest marker runs a
# device-captured overlap-variant mini-sweep that must publish stats
# byte-equivalent to an uncaptured run (same proof style as obs_smoke)
# with `obs devtrace` green over it (measured overlap beside the
# committed static value, op-level fit samples mined); the unit tests
# in the same file pin bucket classification, warmup exclusion, the
# fail-closed contract and the serialized-ring gate on the committed
# golden capture.  Then the committed capture corpus re-parses
# BACKEND-FREE (exit 0: serialized-ring findings downgrade to warnings
# on the single-stream cpu-sim runtime by contract), and the
# β-identification round trip proves out into a THROWAWAY DB: fitting
# the program corpus + the committed devtrace report must identify β
# from the device-timed op samples — no pinned-from-cm1 marker (the
# committed-DB `obs fit` + `obs diff --model cm2` gate runs in
# fit_smoke above).  Zero suppressions.
JAX_PLATFORMS=cpu python -m pytest tests/test_devtrace.py -q \
    -m devtrace_smoke -p no:cacheprovider
DT_TMP="$(mktemp -d)"
python -m dlbb_tpu.cli obs devtrace \
    --journal results/fit_corpus/devtrace/sim8 --output "$DT_TMP"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli obs fit \
    --results results/fit_corpus stats/analysis/devtrace/sim8.json \
    --tier cpu-sim --host calibration --fit-dir "$DT_TMP/db"
python - "$DT_TMP/db/cm2_cpu-sim.json" <<'PY'
import json, sys
v = json.load(open(sys.argv[1]))["versions"][-1]
beta = v["coefficients"]["beta_bytes_per_us"]
assert "pinned" not in beta, f"devtrace_smoke: beta still pinned: {beta}"
assert v.get("device_samples"), "devtrace_smoke: no device samples used"
PY
rm -rf "$DT_TMP"

# compile-ahead sweep-engine smoke (bench/schedule.py is covered by the
# lint pass above; this exercises the pipelined path end-to-end on the
# simulated mesh — 2-op mini-sweep, compile accounting, manifest)
JAX_PLATFORMS=cpu python -m pytest tests/test_bench.py -q \
    -m pipeline_smoke -p no:cacheprovider

# overlapped collective-matmul smoke (docs/overlap.md): tp_overlap
# ring/bidir forward must match the GSPMD fused path on the simulated
# dp2 x tp4 mesh (the HLO-side decomposition contract is enforced by the
# audit above via the overlap targets in the default registry)
JAX_PLATFORMS=cpu python -m pytest tests/test_collective_matmul.py -q \
    -m overlap_smoke -p no:cacheprovider

# chaos smoke (docs/resilience.md): the fault matrix through the real
# sweep engine — transient retry, NaN-stat refusal, torn-write resume
# re-validation, hung-unit watchdog quarantine, SIGTERM journaled stop,
# corrupted-checkpoint fallback — each injection deterministic, each
# invariant asserted (no corrupt artifact survives; resume completes the
# grid).  The subprocess SIGKILL class runs in the slow tier
# (tests/test_resilience.py::test_chaos_gate_kill_class).
JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
    -m chaos_smoke -p no:cacheprovider

# serving smoke (docs/serving.md): a seeded 30-request Poisson
# mini-trace through the continuous-batching engine on the simulated
# dp2 x tp4 mesh — zero rejected-by-bug requests (queue capacity covers
# the whole trace, so any rejection is an engine bug), a schema-valid
# span-trace file, journaled request lifecycle, metrics.prom export,
# and the bench artifact set.  The HLO-side serving contract (decode =
# tiny tp collectives only, activation byte ceiling proving no
# KV-cache regather, donated cache carry) is enforced by `analyze all`
# above via the serve/engine.py targets in the default registry, and
# regression-gated by `analyze diff` against the committed baselines —
# zero suppressions.
JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py -q \
    -m serve_smoke -p no:cacheprovider

# serve_fastpath_smoke (docs/serving.md): the decode fast path's
# equivalence contract — the per-step and fused-K engines must produce
# IDENTICAL completed-token sequences on a seeded mini-trace (fused
# scans, in-flight window, chunked prefill all engaged), with
# schema-valid artifacts and the fast-path metrics counters present.
# The HLO-side contract for the three new jit families (fused-scan
# decode: trip-count-weighted tiny tp psums only; chunked prefill:
# prefix-carry attention with zero cache reads across the slot shard;
# compaction: zero collectives) is enforced by `analyze all` above via
# the serve/engine.py::{decode_fused,prefill_chunk,compact_*} targets,
# and `analyze diff` against the committed baselines makes a cache
# regather inside the scan body a CI failure — zero suppressions.
JAX_PLATFORMS=cpu python -m pytest tests/test_serve_fastpath.py -q \
    -m serve_fastpath_smoke -p no:cacheprovider

# prefix_smoke (docs/serving.md, "Prefix cache & quantized KV"): the
# shared-prefix / quantized-KV equivalence contract — the prefix-cached
# fp engine must produce IDENTICAL completed-token sequences to the
# no-sharing engine on a seeded shared-prefix mini-trace (an attach
# copies the exact block values the skipped chunks would have
# computed), the int8 engine completes the same trace, the trie's
# refcount/CoW accounting drains to zero shared blocks, and the bench
# artifacts carry prefix-attach journal events + hit counters + the
# quantized HBM record.  The HLO-side contract (shared-prefix attach =
# ZERO collectives; int8 decode's donated carry priced from the
# quantized layout) is enforced by `analyze all` above via the
# serve/engine.py::{prefix_attach,decode_step[int8]} targets, and
# `analyze diff` against the committed baselines — zero suppressions.
JAX_PLATFORMS=cpu python -m pytest tests/test_prefix.py -q \
    -m prefix_smoke -p no:cacheprovider

# serve_chaos_smoke (docs/resilience.md, serving faults): the serving
# fault matrix through the real continuous-batching engine on the
# simulated mesh — seeded mini-trace per serving fault class asserting
# transient prefill/decode dispatch failures retry after rolling the
# host ledger/slot state back to the pre-dispatch snapshot, exhausted
# retries fail only the affected requests (journaled request-failed
# with exception chains, never the run), the EMA-scaled watchdog
# abandons a hung dispatch and the engine continues on a fresh carry,
# torn bookkeeping replays, blown-SLO queue heads shed with
# reason=deadline, no corrupt artifact survives, and SIGTERM-mid-trace
# + `cli serve --resume` reproduces the uninterrupted artifact set
# (names + schema + per-request outcomes for non-preempted requests).
# The decode hot path stays provably injection-free: the static
# zero-instruction pin on the fused-scan body runs in this same file.
JAX_PLATFORMS=cpu python -m pytest tests/test_serve_resilience.py -q \
    -m serve_chaos_smoke -p no:cacheprovider

# spec_smoke (docs/serving.md, "Speculative decoding"): draft-and-verify
# multi-token decode — n-gram and draft-model drafters, per-step and
# fused, must stay TOKEN-IDENTICAL to the per-step greedy oracle on a
# seeded repeating-structure mini-trace (speculation buys forwards,
# never different results), with spec-verify journal events and
# acceptance counters exported.  The HLO-side contract (one fused
# (γ+1)-wide verify forward with per-layer psums only — NO per-draft-
# token collectives or trip-weighted loops — and the 1-layer draft
# plane's own donated cache) is enforced by `analyze all` above via the
# serve/engine.py::{verify_step,draft_scan,decode_fused_token} targets,
# and `analyze diff` against the committed baselines makes a per-token
# collective inside the verify body a CI failure — zero suppressions.
JAX_PLATFORMS=cpu python -m pytest tests/test_speculative.py -q \
    -m spec_smoke -p no:cacheprovider

# fleet_smoke (docs/fleet.md): replica-level fault tolerance — the
# 2-replica fleet supervisor on the simulated mesh must route
# deterministically (least-loaded with prefix affinity), survive a
# mid-trace replica kill with every resident failed over and the
# completed tokens byte-identical to the single-engine oracle, walk
# the degradation ladder monotonically with every transition journaled
# and counted, and write the full fleet artifact family (fleet report
# + manifest fault_domains + per-replica journal tracks + the
# failover/hedge/degrade metric families).  The supervisor stays
# provably host-side: the zero-injection pin asserts serve/fleet.py
# builds no device program at all, so a fleet (or a fault plan) can
# never change the jitted prefill/decode HLO the audits above pin.
JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q \
    -m fleet_smoke -p no:cacheprovider
FLEET_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli serve --simulate 8 \
    --requests 8 --rate 80 --seed 11 --replicas 2 \
    --output "$FLEET_TMP" >/dev/null
grep -q 'dlbb_serve_failovers_total' "$FLEET_TMP/metrics.prom" \
    || { echo "fleet_smoke: metrics.prom lost the failover counters"; \
         exit 1; }
grep -q '"fault_domains"' "$FLEET_TMP/serving_manifest.json" \
    || { echo "fleet_smoke: manifest lost the fault_domains record"; \
         exit 1; }
rm -rf "$FLEET_TMP"

# autotune_smoke (docs/autotune.md): the cm2-driven plan autotuner —
# full-grid accounting (searched == pruned + ranked, every pruned point
# journaled with a vocabulary reason), deterministic tie-broken ranking,
# fail-closed on a missing cm2 fit, the pinned calibration-grid
# agreement regression (top-2 contains the measured winner for >= 70%
# of the committed baseline families), and one measured top-1 vs
# default-heuristic run through the real serving engine.  The CLI run
# below exercises the static observability surface end-to-end:
# sweep_manifest.json search accounting + the plan_search_points /
# plan_agreement_ratio series in metrics.prom.
JAX_PLATFORMS=cpu python -m pytest tests/test_autotune.py -q \
    -m autotune_smoke -p no:cacheprovider
PLAN_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m dlbb_tpu.cli plan --auto --simulate 8 \
    --no-measure --output "$PLAN_TMP"
grep -q 'dlbb_plan_search_points_total{outcome="searched"}' \
    "$PLAN_TMP/metrics.prom" \
    || { echo "autotune_smoke: metrics.prom lost the search counters"; \
         exit 1; }
grep -q 'dlbb_plan_agreement_ratio{scope="calibration-grid"}' \
    "$PLAN_TMP/metrics.prom" \
    || { echo "autotune_smoke: metrics.prom lost the agreement gauge"; \
         exit 1; }
grep -q '"searched"' "$PLAN_TMP/sweep_manifest.json" \
    || { echo "autotune_smoke: manifest lost the search accounting"; \
         exit 1; }
rm -rf "$PLAN_TMP"

# compressed-collective smoke (docs/compression.md): int8/fp8 allreduce_q
# mini-sweep through the real engine + one compressed train step whose
# losses track the uncompressed run — the HLO-side compression proof
# (pure quantised ring, total wire <= 0.55x the bf16 baseline, scale side
# channel included) is enforced by the audit above via the compressed
# targets in the default registry, with zero suppressions
JAX_PLATFORMS=cpu python -m pytest tests/test_compression.py -q \
    -m compression_smoke -p no:cacheprovider

echo "comm-lint: clean (report: $REPORT)"
