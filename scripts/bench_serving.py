#!/usr/bin/env python
"""Decode fast-path evidence: per-step vs fused-K x compaction.

Measures the serving engine's decode fast path (docs/serving.md) through
the engine's own trace replay and writes ``BENCH_serve.json`` at the
repo root:

- **throughput grid** — the SAME seeded poisson trace (decode-bound: a
  burst arrival so the batch stays full) replayed through the per-step
  PR-9 engine and the fused-scan engine at K in {4, 16, 64}, plus a
  dp=1 pair pricing slot compaction on/off.  The acceptance bar —
  fused K=16 at >= 1.5x the per-step engine's per-output-token
  throughput on the simulated 8-rank mesh — is recorded as a checked
  claim, not prose.
- **equivalence gate** — before any timing, per-step and fused-K
  engines replay a smoke trace with token capture on and must produce
  IDENTICAL completed-token sequences (the argmax-token contract the
  ``serve_fastpath_smoke`` CI stage also pins); a mismatch aborts the
  bench.

Methodology follows ``scripts/bench_compression.py``: settings are
INTERLEAVED within each repetition so host drift cancels across modes,
and medians of per-rep throughput are reported with min/max spread.

On this image the mesh is CPU-simulated — which is exactly the regime
the fast path targets: host dispatch dominates µs-scale decode steps
(the committed cm1 calibration under-predicts ~289x geomean for this
reason), so collapsing K dispatches into one on-device ``lax.scan`` is
measurable signal, not fabric noise.  The chip row stays keyed
``pending_tunnel`` for the next healthy tunnel window
(``DLBB_TPU_TESTS=1 python scripts/bench_serving.py --chip``).

Usage: python scripts/bench_serving.py [--requests N] [--reps R] [--chip]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

CHIP = "--chip" in sys.argv[1:]
if not CHIP:
    from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

    force_cpu_simulation(8)

import jax  # noqa: E402

from dlbb_tpu.comm.mesh import build_parallelism_mesh  # noqa: E402
from dlbb_tpu.models.configs import ModelConfig  # noqa: E402
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine  # noqa: E402
from dlbb_tpu.serve.traffic import generate_trace  # noqa: E402
from dlbb_tpu.stats.serving_report import write_fastpath_report  # noqa: E402
from dlbb_tpu.utils.simulate import topology_record  # noqa: E402

SERVE = dict(max_batch=8, block_size=16, max_seq=256, queue_capacity=64)

# The bench model: 2-layer MHA at h128 on a dp=8 batch-parallel mesh —
# the DISPATCH-OVERHEAD regime the fast path targets.  On the dp-only
# mesh the decode step lowers to ZERO collectives (audited:
# plan_expected_kinds(dp=8, decode=True) == {}), so the per-step wall
# is device work + per-dispatch host/runtime overhead — exactly the
# cost a fused scan amortises.  The tp4 rows below keep the
# collective-heavy geometry in the grid for honesty: on THIS cpu-sim
# runtime the per-trip collective sync dominates there and fusing
# barely pays (the chip rows re-price that regime on real fabric).
BENCH_MODEL = dict(hidden_size=128, num_layers=2, num_heads=8,
                   num_kv_heads=8, ffn_intermediate=256,
                   dtype="float32", attention="full")

# name -> (mesh key, trace key, ServingConfig fast-path kwargs).  K=1
# IS the per-step PR-9 engine.  The main grid replays the decode-bound
# trace (one aligned admission wave, uniform long outputs — the
# regime the acceptance bar describes); the tp4 rows replay the
# STAGGERED trace (lognormal outputs, so occupancy decays through the
# drain) on identical tp-only topology, pricing compaction on/off
# apples-to-apples where it can actually engage.
SETTINGS = {
    "per_step": ("dp8", "uniform", {}),
    "fused_k4": ("dp8", "uniform",
                 dict(decode_horizon=4, inflight_window=2)),
    "fused_k16": ("dp8", "uniform",
                  dict(decode_horizon=16, inflight_window=2)),
    "fused_k64": ("dp8", "uniform",
                  dict(decode_horizon=64, inflight_window=2)),
    "tp4_per_step": ("tp4", "staggered", {}),
    "tp4_fused_k16": ("tp4", "staggered",
                      dict(decode_horizon=16, inflight_window=2)),
    "tp4_fused_k16_compact": (
        "tp4", "staggered",
        dict(decode_horizon=16, inflight_window=2,
             compact_threshold=0.5)),
}
BASELINE = "per_step"
ACCEPTANCE = {"setting": "fused_k16", "min_speedup": 1.5}


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _build_meshes():
    devs = jax.devices()
    return {
        "dp8": build_parallelism_mesh(data_parallel=8),
        "tp4": build_parallelism_mesh(tensor_parallel=4,
                                      devices=devs[:4]),
    }


def _traces(num_requests: int) -> dict:
    """The two replayed traces (identical per setting, seeded).

    ``uniform``: a burst arrival filling every slot in ONE admission
    wave, uniform long outputs — pure decode-bound replay where the
    event horizon equals the drain, so fused scans reach full K.
    ``staggered``: lognormal outputs, so slots complete at different
    times and occupancy decays through the drain — the regime where
    compaction can engage (and where overshoot masking is exercised).
    """
    return {
        "uniform": generate_trace(
            "poisson", num_requests, seed=11, rate=1e5,
            prompt_range=(8, 16), output_range=(240, 240)),
        "staggered": generate_trace(
            "poisson", num_requests, seed=12, rate=1e5,
            prompt_range=(8, 16), output_range=(32, 240)),
    }


def _equivalence_gate(model_cfg, meshes) -> dict:
    """Per-step vs fused-K token sequences must be identical on a smoke
    trace before any number is published."""
    trace = generate_trace("poisson", 16, seed=3, rate=2000.0,
                           prompt_range=(8, 32), output_range=(8, 24))
    tokens = {}
    for name in ("per_step", "fused_k16"):
        mesh_key, _trace_key, extra = SETTINGS[name]
        engine = ServingEngine(
            model_cfg, ServingConfig(**SERVE, **extra), meshes[mesh_key],
            verbose=False, capture_tokens=True,
        )
        tokens[name] = engine.run_trace(trace)["completed_tokens"]
    identical = tokens["per_step"] == tokens["fused_k16"]
    if not identical:
        raise SystemExit(
            "equivalence gate FAILED: fused-K decode produced different "
            "completed-token sequences than the per-step engine — "
            "refusing to publish throughput for a wrong result"
        )
    return {
        "checked": True,
        "identical": True,
        "requests": len(tokens["per_step"]),
        "tokens": sum(len(v) for v in tokens["per_step"].values()),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=8,
                    help="requests in the replayed trace (default 8 = "
                         "one full admission wave)")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per setting (default 3)")
    ap.add_argument("--chip", action="store_true",
                    help="run on the real TPU chip instead of the "
                         "simulated mesh (fills the chip row)")
    ap.add_argument("--output", default=str(REPO / "BENCH_serve.json"))
    args = ap.parse_args()

    model_cfg = ModelConfig.from_dict(BENCH_MODEL)
    meshes = _build_meshes()
    equivalence = _equivalence_gate(model_cfg, meshes)
    print(f"[equivalence] per-step == fused_k16 over "
          f"{equivalence['tokens']} tokens: OK")

    traces = _traces(args.requests)
    engines = {}
    for name, (mesh_key, _trace_key, extra) in SETTINGS.items():
        engines[name] = ServingEngine(
            model_cfg, ServingConfig(**SERVE, **extra), meshes[mesh_key],
            verbose=False,
        )
    # absorb compiles + first-dispatch costs outside the timed reps
    for name, (_m, trace_key, _e) in SETTINGS.items():
        engines[name].run_trace(traces[trace_key])

    per_rep: dict[str, list[dict]] = {name: [] for name in SETTINGS}
    for _ in range(args.reps):
        for name, (_m, trace_key, _e) in SETTINGS.items():
            report = engines[name].run_trace(traces[trace_key])
            per_rep[name].append({
                "tok_s": report["goodput_tokens_per_s"],
                "per_token_p50_s":
                    report["per_token_latency"]["median"],
                "decode_units": report["decode_units"],
                "decode_steps": report["decode_steps"],
                "fused_steps": report["fast_path"]["fused_steps"],
                "compacted_scans":
                    report["fast_path"]["compacted_scans"],
            })

    settings_out = {}
    for name, (mesh_key, trace_key, extra) in SETTINGS.items():
        reps = per_rep[name]
        tok = [r["tok_s"] for r in reps]
        settings_out[name] = {
            "mesh": mesh_key,
            "trace": trace_key,
            "decode_horizon": extra.get("decode_horizon", 1),
            "inflight_window": extra.get("inflight_window", 1),
            "compact_threshold": extra.get("compact_threshold"),
            "output_tokens_per_s": {
                "median": _median(tok), "min": min(tok), "max": max(tok),
                "reps": tok,
            },
            "per_token_p50_ms": round(
                _median([r["per_token_p50_s"] for r in reps]) * 1e3, 3),
            "decode_units": _median([r["decode_units"] for r in reps]),
            "decode_steps": _median([r["decode_steps"] for r in reps]),
            "fused_steps": _median([r["fused_steps"] for r in reps]),
            "compacted_scans": _median(
                [r["compacted_scans"] for r in reps]),
        }
    # speedups are within-mesh, within-trace: the dp8 grid prices
    # against per_step, the tp4 compaction rows against tp4_per_step
    for name, (mesh_key, _t, _e) in SETTINGS.items():
        base_name = "tp4_per_step" if mesh_key == "tp4" else BASELINE
        base_med = settings_out[base_name]["output_tokens_per_s"]["median"]
        med = settings_out[name]["output_tokens_per_s"]["median"]
        settings_out[name]["baseline"] = base_name
        settings_out[name]["speedup_vs_per_step"] = round(
            med / base_med, 3)
    acc = settings_out[ACCEPTANCE["setting"]]["speedup_vs_per_step"]
    acceptance = {
        **ACCEPTANCE,
        "measured_speedup": acc,
        "passed": acc >= ACCEPTANCE["min_speedup"],
    }

    backend = jax.default_backend()
    payload = {
        "harness": "scripts/bench_serving.py",
        "schema": "dlbb_bench_serve_v1",
        "model": dict(BENCH_MODEL),
        "serving": dict(SERVE),
        "traces": {
            key: {"kind": t.kind, "requests": len(t), "seed": t.seed,
                  "params": dict(t.params)}
            for key, t in traces.items()
        },
        "repetitions": args.reps,
        "baseline": BASELINE,
        "methodology": (
            "identical seeded trace replayed through every engine; "
            "settings interleaved within each repetition; medians of "
            "per-rep goodput with min/max spread; equivalence gate "
            "(identical argmax-token sequences) run before any timing"
        ),
        "backend": backend,
        "topology": topology_record(),
        "jax_version": jax.__version__,
        "host_cpu_count": os.cpu_count(),
        "timestamp": time.time(),
        "equivalence": equivalence,
        "settings": settings_out,
        "acceptance": acceptance,
        "claim": (
            "CPU-simulated mesh: per-decode-step wall is dominated by "
            "host dispatch (the committed cm1 calibration under-"
            "predicts ~289x geomean for exactly this reason), which is "
            "the overhead the fused scan removes — K dispatches become "
            "one lax.scan.  Fabric-sensitive deltas (compaction's "
            "gather cost on a real interconnect) re-price on chip."
            if backend == "cpu" else
            "chip run: walls are device-honest; the fused rows price "
            "real dispatch amortisation on hardware."
        ),
        "chip": (
            {"status": "measured", "backend": backend}
            if backend != "cpu" else {
                "status": "pending_tunnel",
                "note": ("chip rows keyed for the next healthy tunnel "
                         "window: DLBB_TPU_TESTS=1 python "
                         "scripts/bench_serving.py --chip"),
            }
        ),
    }
    atomic_write_text(json.dumps(payload, indent=1) + "\n",
                      Path(args.output))
    write_fastpath_report(Path(args.output), REPO / "stats" / "serving")
    for name in SETTINGS:
        s = settings_out[name]
        tps = s["output_tokens_per_s"]
        print(f"[{name:22s}] {tps['median']:8.1f} tok/s "
              f"({tps['min']:.1f}..{tps['max']:.1f})  "
              f"x{s['speedup_vs_per_step']:.2f} vs per-step, "
              f"{s['decode_units']} dispatches")
    print(f"[acceptance] {ACCEPTANCE['setting']} >= "
          f"{ACCEPTANCE['min_speedup']}x: "
          f"{'PASS' if acceptance['passed'] else 'FAIL'} "
          f"({acc:.2f}x)")
    print(f"BENCH_serve.json -> {args.output}")
    return 0 if acceptance["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
