#!/usr/bin/env python
"""Speculative-decoding evidence: draft-and-verify vs the fused scan.

Measures the serving engine's speculative decode (docs/serving.md,
"Speculative decoding") through the engine's own trace replay and writes
``BENCH_spec.json`` at the repo root:

- **equivalence gate first** — every token-feedback setting (greedy,
  ngram, draft-model) replays the bench trace with token capture on and
  must produce completed-token sequences IDENTICAL to the per-step
  greedy oracle engine's; a mismatch aborts the bench before any number
  is published.  The ``off`` rows are the LEGACY continuous-feedback
  engine — their sequences differ from the token-quantised modes by
  design (the equivalence-gate weakening the tentpole documents), so
  they are throughput baselines, not identity subjects.
- **throughput grid** — {off, ngram γ in {2,4,8,16}, draft-model γ4}
  x {per-step, fused K16} over the SAME repeating-structure seeded
  trace (``prompt_period`` motif prompts + greedy-feedback cycles give
  the n-gram drafter real lookup structure).  Per-output-token
  throughput with TTFT/TPOT; speculation rows also record acceptance
  rate, mean accepted length, and draft overhead.  The acceptance bar
  — ngram γ16 at >= 1.2x the non-speculative fused-K16 engine — is
  recorded as a checked claim, not prose.

Methodology follows ``scripts/bench_serving.py``: one warmup replay per
engine absorbs compiles, settings are INTERLEAVED within each timed
repetition so host drift cancels, and medians of per-rep throughput are
reported with min/max spread.

On this image the mesh is CPU-simulated, which UNDERSELLS speculation:
each verify unit pays a host sync (commits must land before host
bookkeeping) that the fused scan amortises over K trips, and the
(γ+1)-wide verify forward is priced at its real FLOPs rather than the
weights-bound cost a real chip would give it.  The sim rows are honest
about that regime; the chip row stays keyed ``pending_tunnel`` for the
next healthy tunnel window (``DLBB_TPU_TESTS=1 python
scripts/bench_speculative.py --chip``).

Usage: python scripts/bench_speculative.py [--requests N] [--reps R]
       [--chip]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

CHIP = "--chip" in sys.argv[1:]
if not CHIP:
    from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

    force_cpu_simulation(8)

import jax  # noqa: E402

from dlbb_tpu.comm.mesh import build_parallelism_mesh  # noqa: E402
from dlbb_tpu.models.configs import ModelConfig  # noqa: E402
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine  # noqa: E402
from dlbb_tpu.serve.traffic import generate_trace  # noqa: E402
from dlbb_tpu.stats.serving_report import (  # noqa: E402
    write_speculative_report,
)
from dlbb_tpu.utils.simulate import topology_record  # noqa: E402

SERVE = dict(max_batch=8, block_size=8, max_seq=160, queue_capacity=64)

# The bench model: the 2-layer tiny transformer on a dp2 x tp4 mesh —
# the SAME collective geometry the verify-step audit targets pin.
# Greedy argmax feedback through the fixed token table falls into short
# cycles within a few dozen tokens; with 96-128-token outputs the
# n-gram drafter's cyclic extension locks onto them, which is exactly
# the repeating-structure regime prompt-lookup drafting targets.
BENCH_MODEL = dict(hidden_size=64, num_layers=2, num_heads=4,
                   ffn_intermediate=128, dtype="float32",
                   attention="full")

FUSED = dict(decode_horizon=16)

# name -> ServingConfig kwargs.  "off" is the legacy continuous-feedback
# engine (the pre-speculation fast path); "greedy" is token feedback
# without drafting — the per-step greedy row IS the token-identity
# oracle every speculative setting is gated against.
SETTINGS = {
    "off_per_step": dict(speculation="off"),
    "off_fused16": dict(speculation="off", **FUSED),
    "greedy_per_step": dict(speculation="greedy"),
    "greedy_fused16": dict(speculation="greedy", **FUSED),
    "ngram_g2_per_step": dict(speculation="ngram", spec_gamma=2),
    "ngram_g2_fused16": dict(speculation="ngram", spec_gamma=2, **FUSED),
    "ngram_g4_per_step": dict(speculation="ngram", spec_gamma=4),
    "ngram_g4_fused16": dict(speculation="ngram", spec_gamma=4, **FUSED),
    "ngram_g8_per_step": dict(speculation="ngram", spec_gamma=8),
    "ngram_g8_fused16": dict(speculation="ngram", spec_gamma=8, **FUSED),
    "ngram_g16_fused16": dict(speculation="ngram", spec_gamma=16, **FUSED),
    "draft_g4_per_step": dict(speculation="draft-model", spec_gamma=4,
                              spec_draft_layers=1),
    "draft_g4_fused16": dict(speculation="draft-model", spec_gamma=4,
                             spec_draft_layers=1, **FUSED),
}
ORACLE = "greedy_per_step"
BASELINE = "off_fused16"
ACCEPTANCE = {"setting": "ngram_g16_fused16", "baseline": BASELINE,
              "min_speedup": 1.2}


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _bench_trace(num_requests: int):
    """The replayed repeating-structure trace: burst-ish poisson so the
    batch fills in one wave, motif prompts (period 4), long outputs so
    the greedy-feedback cycles dominate the drafted region."""
    return generate_trace(
        "poisson", num_requests, seed=7, rate=500.0,
        prompt_range=(8, 16), output_range=(96, 128), prompt_period=4)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16,
                    help="requests in the replayed trace (default 16 = "
                         "two admission waves)")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per setting (default 3)")
    ap.add_argument("--chip", action="store_true",
                    help="run on the real TPU chip instead of the "
                         "simulated mesh (fills the chip row)")
    ap.add_argument("--output", default=str(REPO / "BENCH_spec.json"))
    args = ap.parse_args()

    model_cfg = ModelConfig.from_dict(BENCH_MODEL)
    mesh = build_parallelism_mesh(data_parallel=2, tensor_parallel=4)
    trace = _bench_trace(args.requests)

    # equivalence gate FIRST, on the published trace, with dedicated
    # capture engines (token capture syncs every unit, so the timed
    # engines below run with it off): every token-feedback setting must
    # match the per-step greedy oracle's completed sequences
    def _captured_tokens(extra):
        eng = ServingEngine(
            model_cfg, ServingConfig(**SERVE, **extra), mesh,
            verbose=False, capture_tokens=True)
        return eng.run_trace(trace)["completed_tokens"]

    oracle_tokens = _captured_tokens(SETTINGS[ORACLE])
    identity = {}
    for name, extra in SETTINGS.items():
        if extra.get("speculation", "off") == "off" or name == ORACLE:
            continue
        identity[name] = _captured_tokens(extra) == oracle_tokens
    if not all(identity.values()):
        bad = sorted(n for n, ok in identity.items() if not ok)
        raise SystemExit(
            "equivalence gate FAILED: speculative decode produced "
            f"different completed-token sequences than the per-step "
            f"greedy oracle for {bad} — refusing to publish throughput "
            "for a wrong result"
        )
    n_tok = sum(len(v) for v in oracle_tokens.values())
    print(f"[equivalence] {len(identity)} settings == {ORACLE} over "
          f"{n_tok} tokens: OK")

    # timed engines: capture off, one untimed warmup replay each to
    # absorb compiles, then interleaved timed repetitions
    engines = {
        name: ServingEngine(
            model_cfg, ServingConfig(**SERVE, **extra), mesh,
            verbose=False)
        for name, extra in SETTINGS.items()
    }
    for eng in engines.values():
        eng.run_trace(trace)

    per_rep: dict[str, list[dict]] = {name: [] for name in SETTINGS}
    for _ in range(args.reps):
        for name, eng in engines.items():
            t0 = time.perf_counter()
            report = eng.run_trace(trace)
            wall = time.perf_counter() - t0
            spec = report.get("speculation", {})
            per_rep[name].append({
                "tok_s": report["completed_output_tokens"] / wall,
                "ttft_p50_s": report["ttft"]["median"],
                "per_token_p50_s": report["per_token_latency"]["median"],
                "decode_units": report["decode_units"],
                "verify_units": spec.get("verify_units", 0),
                "fallback_units": spec.get("fallback_units", 0),
                "acceptance_rate": spec.get("acceptance_rate"),
                "mean_accepted_len": spec.get("mean_accepted_len"),
                "draft_overhead_s": spec.get("draft_overhead_s"),
            })

    settings_out = {}
    for name, extra in SETTINGS.items():
        reps = per_rep[name]
        tok = [r["tok_s"] for r in reps]
        acc = [r["acceptance_rate"] for r in reps
               if r["acceptance_rate"] is not None]
        mal = [r["mean_accepted_len"] for r in reps
               if r["mean_accepted_len"] is not None]
        draft = [r["draft_overhead_s"] for r in reps
                 if r["draft_overhead_s"] is not None]
        settings_out[name] = {
            "speculation": extra.get("speculation", "off"),
            "spec_gamma": extra.get("spec_gamma"),
            "decode_horizon": extra.get("decode_horizon", 1),
            "output_tokens_per_s": {
                "median": _median(tok), "min": min(tok), "max": max(tok),
                "reps": tok,
            },
            "ttft_p50_ms": round(
                _median([r["ttft_p50_s"] for r in reps]) * 1e3, 3),
            "per_token_p50_ms": round(
                _median([r["per_token_p50_s"] for r in reps]) * 1e3, 3),
            "decode_units": _median([r["decode_units"] for r in reps]),
            "verify_units": _median([r["verify_units"] for r in reps]),
            "fallback_units": _median(
                [r["fallback_units"] for r in reps]),
            "acceptance_rate": (round(_median(acc), 4) if acc else None),
            "mean_accepted_len": (round(_median(mal), 3) if mal else None),
            "draft_overhead_s": (round(_median(draft), 4)
                                 if draft else None),
            "token_identical": identity.get(name),
        }
    # speedups are regime-matched: per-step rows price against the
    # legacy per-step engine, fused rows against the non-speculative
    # fused K16 engine — "what does drafting buy on top of the engine
    # you already run"
    for name, extra in SETTINGS.items():
        base_name = ("off_fused16" if extra.get("decode_horizon")
                     else "off_per_step")
        base_med = settings_out[base_name]["output_tokens_per_s"]["median"]
        med = settings_out[name]["output_tokens_per_s"]["median"]
        settings_out[name]["baseline"] = base_name
        settings_out[name]["speedup_vs_baseline"] = round(
            med / base_med, 3)
    acc_row = settings_out[ACCEPTANCE["setting"]]
    acceptance = {
        **ACCEPTANCE,
        "measured_speedup": acc_row["speedup_vs_baseline"],
        "passed": (acc_row["speedup_vs_baseline"]
                   >= ACCEPTANCE["min_speedup"]),
    }

    backend = jax.default_backend()
    payload = {
        "harness": "scripts/bench_speculative.py",
        "schema": "dlbb_bench_spec_v1",
        "model": dict(BENCH_MODEL),
        "serving": dict(SERVE),
        "mesh": {"dp": 2, "tp": 4},
        "trace": {"kind": trace.kind, "requests": len(trace),
                  "seed": trace.seed, "params": dict(trace.params)},
        "repetitions": args.reps,
        "baseline": BASELINE,
        "oracle": ORACLE,
        "methodology": (
            "identical repeating-structure seeded trace replayed "
            "through every engine; settings interleaved within each "
            "repetition; medians of per-rep completed-output-token "
            "throughput with min/max spread; greedy token-identity "
            "gate (every token-feedback setting == the per-step greedy "
            "oracle) run on the published trace before any timing"
        ),
        "backend": backend,
        "topology": topology_record(),
        "jax_version": jax.__version__,
        "host_cpu_count": os.cpu_count(),
        "timestamp": time.time(),
        "equivalence": {
            "checked": True,
            "oracle": ORACLE,
            "identical": dict(sorted(identity.items())),
            "tokens": n_tok,
            "note": ("off rows are the legacy continuous-feedback "
                     "engine: different sequences by design (the "
                     "documented equivalence-gate weakening), so they "
                     "are baselines, not identity subjects"),
        },
        "settings": settings_out,
        "acceptance": acceptance,
        "claim": (
            "CPU-simulated mesh: every verify unit pays a host sync "
            "(host bookkeeping needs the commit counts) that the fused "
            "scan amortises over K trips, and the (γ+1)-wide verify "
            "forward is priced at real FLOPs, not the weights-bound "
            "cost a chip gives it — so these rows UNDERSELL "
            "speculation; acceptance-rate and accepted-length columns "
            "are regime-independent signal."
            if backend == "cpu" else
            "chip run: walls are device-honest; verify forwards price "
            "weights-bound, the regime speculative decoding targets."
        ),
        "chip": (
            {"status": "measured", "backend": backend}
            if backend != "cpu" else {
                "status": "pending_tunnel",
                "note": ("chip rows keyed for the next healthy tunnel "
                         "window: DLBB_TPU_TESTS=1 python "
                         "scripts/bench_speculative.py --chip"),
            }
        ),
    }
    atomic_write_text(json.dumps(payload, indent=1) + "\n",
                      Path(args.output))
    write_speculative_report(Path(args.output), REPO / "stats" / "serving")
    for name in SETTINGS:
        s = settings_out[name]
        tps = s["output_tokens_per_s"]
        acc_s = ("-" if s["acceptance_rate"] is None
                 else f"{s['acceptance_rate']:.2f}")
        print(f"[{name:20s}] {tps['median']:8.1f} tok/s "
              f"({tps['min']:.1f}..{tps['max']:.1f})  "
              f"x{s['speedup_vs_baseline']:.2f} vs {s['baseline']}, "
              f"acc={acc_s}")
    print(f"[acceptance] {ACCEPTANCE['setting']} >= "
          f"{ACCEPTANCE['min_speedup']}x vs {BASELINE}: "
          f"{'PASS' if acceptance['passed'] else 'FAIL'} "
          f"({acceptance['measured_speedup']:.2f}x)")
    print(f"BENCH_spec.json -> {args.output}")
    return 0 if acceptance["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
