#!/usr/bin/env python
"""Cross-validate the chained timing estimates against forced single-
iteration completions (VERDICT r1 weak #4/#8).

The chained mode estimates per-iteration time as
``(fori_loop(M iterations) wall - fetch overhead) / M``.  The independent
check here times ONE iteration to true completion via a data-dependent
scalar fetch (enqueue cannot satisfy it), minus the calibrated fetch
overhead.  The two must agree to within the dispatch noise; the single-
iteration estimate is biased UP by one tunnel roundtrip, so chained <=
single-iteration is the expected ordering on a remote-async backend.

Writes ``results/timing_crosscheck.json`` with both estimates for the
headline configs.  Run on the real TPU chip (no --simulate): that is the
backend whose honesty is in question.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402


def main() -> int:
    import jax
    import jax.numpy as jnp

    from dlbb_tpu.models.configs import MODEL_CONFIGS
    from dlbb_tpu.models.transformer import forward, init_params
    from dlbb_tpu.utils.timing import (
        resolve_timing_mode,
        single_iteration_estimate,
        time_fn_chained,
    )

    checks = []
    for size, attention in (("1B", "simplified"), ("1B", "full")):
        config = MODEL_CONFIGS[size].with_(attention=attention)
        params = init_params(config, jax.random.key(42))
        batch = jax.random.normal(
            jax.random.key(0), (8, 512, config.hidden_size),
            dtype=jnp.bfloat16,
        )
        step = jax.jit(lambda p, x, c=config: forward(p, x, c))

        # the timing loop DONATES batch; the returned carry replaces it
        # for the forced-completion estimate below
        chained, meta, batch = time_fn_chained(
            step, batch, warmup=2, iterations=20, chunk_size=5,
            op_args=(params,),
        )
        chained_mean = sum(chained) / len(chained)
        single = single_iteration_estimate(
            step, batch, trials=5, op_args=(params,)
        )
        ratio = single / chained_mean if chained_mean > 0 else float("inf")
        checks.append({
            "config": f"{size}_{attention}_b8_s512",
            "chained_mean_s": chained_mean,
            "single_iteration_s": single,
            "single_over_chained": ratio,
            "fetch_overhead_s": meta["fetch_overhead_s"],
        })
        print(f"{size}/{attention}: chained {chained_mean * 1e3:.2f} ms, "
              f"single-forced {single * 1e3:.2f} ms, ratio {ratio:.3f}",
              flush=True)

    out = {
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "timing_mode_auto": resolve_timing_mode("auto"),
        "method": __doc__.strip().splitlines()[0],
        "checks": checks,
        "timestamp": time.time(),
    }
    path = REPO / "results" / "timing_crosscheck.json"
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(json.dumps(out, indent=2) + "\n", path)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
