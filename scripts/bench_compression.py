#!/usr/bin/env python
"""Compressed-vs-uncompressed evidence for the quantised collectives.

Measures the compression axis (docs/compression.md) through the
framework's own timed regions and writes ``BENCH_compress.json`` at the
repo root:

- **micro** — ``allreduce_q`` / ``reducescatter_q`` under the
  ``compress_int8`` / ``compress_fp8`` / ``compress_int8_bf16acc``
  variants vs their uncompressed counterparts, swept through the PR-3
  engine (work-unit dedup, payload avals, measurement gate), with the
  ANALYTIC bytes-on-wire of each row (scale side channel included) from
  ``analysis/expectations.op_wire_bytes`` — the same model the comm-lint
  byte ceiling audits against the compiled HLO;
- **train** — loss-curve divergence of the int8/fp8 error-feedback runs
  vs the uncompressed DDP run over a short horizon.  Divergence beyond
  tolerance or a NaN blowup raises ``CorruptStats`` (the chaos harness's
  taxonomy) and lands as a quarantined row, never a silent pass.

Methodology follows ``scripts/bench_overlap.py``: settings are
INTERLEAVED within each repetition so host drift cancels across modes,
and medians-of-medians are reported with min/max spread.

On this image the mesh is CPU-simulated: a ppermute is a memcpy, so wall
clocks carry no fabric signal — the committed claim is **correctness +
wire volume** (equivalence pinned by tests/test_compression.py, the byte
ceiling by the comm-lint audit), with the chip perf row keyed
``pending_tunnel`` for the next healthy tunnel window
(``DLBB_TPU_TESTS=1 python scripts/bench_compression.py --chip``).

Usage: python scripts/bench_compression.py [--iters N] [--reps R]
       [--steps S] [--chip]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

CHIP = "--chip" in sys.argv[1:]
if not CHIP:
    from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

    force_cpu_simulation(8)

import jax  # noqa: E402

from dlbb_tpu.analysis.expectations import op_wire_bytes  # noqa: E402
from dlbb_tpu.bench.runner import Sweep1D, run_sweep  # noqa: E402
from dlbb_tpu.resilience.errors import CorruptStats  # noqa: E402
from dlbb_tpu.train.loop import run_train  # noqa: E402
from dlbb_tpu.utils.simulate import topology_record  # noqa: E402

# measurement settings, interleaved per repetition: the uncompressed
# baseline ops under the default variant, the quantised ops under each
# compress_* variant
SETTINGS = (
    ("baseline_bf16", "default", ("allreduce", "reducescatter")),
    ("int8", "compress_int8", ("allreduce_q", "reducescatter_q")),
    ("fp8", "compress_fp8", ("allreduce_q", "reducescatter_q")),
    ("int8_bf16acc", "compress_int8_bf16acc",
     ("allreduce_q", "reducescatter_q")),
)
# compressed op -> the uncompressed op its step-time delta is against
BASELINE_OF = {"allreduce_q": "allreduce", "reducescatter_q": "reducescatter"}

SIZE_LABEL, SIZE_ELEMS = "64KB", 16384
RANKS = 8

# loss-divergence tolerances (max per-step relative difference vs the
# uncompressed run) — beyond these the row is QUARANTINED via CorruptStats
TRAIN_TOL = {"int8": 0.05, "fp8": 0.10}


def _micro_run(variant: str, operations, work: Path, iters: int) -> dict:
    out = work / f"micro_{variant}_{time.monotonic_ns()}"
    sweep = Sweep1D(
        implementation="bench_compress",
        variant=variant,
        operations=operations,
        data_sizes=((SIZE_LABEL, SIZE_ELEMS),),
        rank_counts=(RANKS,),
        dtype="bfloat16",
        warmup_iterations=2,
        measurement_iterations=iters,
        output_dir=str(out),
        compile_cache="off",
    )
    files = run_sweep(sweep, verbose=False)
    medians = {}
    for f in files:
        d = json.loads(Path(f).read_text())
        flat = sorted(t for row in d["timings"] for t in row)
        medians[d["operation"]] = flat[len(flat) // 2]
    return medians


def _train_run(compression: str, steps: int) -> list[float]:
    config = {
        "experiment": {"name": f"compress_{compression}"},
        "model": {"hidden_size": 64, "num_layers": 2, "num_heads": 4,
                  "ffn_intermediate": 128, "attention": "full",
                  "dtype": "float32"},
        "parallelism": {"world_size": 1, "data_parallel": 8},
        "input": {"batch_size": 8, "sequence_length": 32, "seed": 42},
        "execution": {"warmup_iterations": 1,
                      "benchmark_iterations": steps},
        "training": {"learning_rate": 1e-2,
                     **({"grad_compression": compression}
                        if compression != "none" else {})},
    }
    return [float(v) for v in run_train(config, verbose=False)["losses"]]


def _check_divergence(name: str, losses, ref, tol: float) -> float:
    """Max per-step relative divergence; CorruptStats on NaN/blowup —
    the same refusal taxonomy the sweep engine uses for poisoned stats."""
    import math

    if not all(math.isfinite(v) for v in losses):
        raise CorruptStats(
            f"{name}: non-finite loss in {losses} — refusing to publish"
        )
    div = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(ref, losses))
    if div > tol:
        raise CorruptStats(
            f"{name}: loss divergence {div:.4f} exceeds tolerance {tol} "
            f"vs the uncompressed run"
        )
    return div


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _spread(vals):
    return {
        "median_s": _median(vals),
        "min_s": min(vals),
        "max_s": max(vals),
        "repetitions": len(vals),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20,
                    help="measured iterations per config (default 20)")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per setting (default 3)")
    ap.add_argument("--steps", type=int, default=10,
                    help="train steps for the loss-divergence run")
    ap.add_argument("--chip", action="store_true",
                    help="run on the real TPU chip instead of the "
                         "simulated mesh (fills the chip row)")
    ap.add_argument("--output", default=str(REPO / "BENCH_compress.json"))
    args = ap.parse_args()

    work = Path(tempfile.mkdtemp(prefix="bench_compress_"))
    micro: dict[str, list[dict]] = {name: [] for name, _, _ in SETTINGS}
    try:
        # absorb process one-time costs (imports, first dispatch)
        _micro_run("default", ("allreduce",), work, 3)
        for _ in range(args.reps):
            for name, variant, operations in SETTINGS:
                micro[name].append(
                    _micro_run(variant, operations, work, args.iters))
    finally:
        shutil.rmtree(work, ignore_errors=True)

    micro_out = {}
    for name, variant, operations in SETTINGS:
        compression = None if name == "baseline_bf16" else \
            ("fp8" if name == "fp8" else "int8")
        per_op = {}
        for op in operations:
            per_op[op] = _spread([rep[op] for rep in micro[name]])
            per_op[op]["bytes_on_wire"] = op_wire_bytes(
                op, SIZE_ELEMS, RANKS, 2, compression=compression)
        micro_out[name] = per_op
    # step-time delta + wire ratio of each compressed row vs its baseline
    for name in ("int8", "fp8", "int8_bf16acc"):
        for op, base_op in BASELINE_OF.items():
            row = micro_out[name][op]
            base = micro_out["baseline_bf16"][base_op]
            row["vs_uncompressed"] = {
                "baseline_op": base_op,
                "step_time_ratio": row["median_s"] / base["median_s"],
                "wire_bytes_ratio": (
                    row["bytes_on_wire"] / base["bytes_on_wire"]),
            }

    # ---- train-side loss divergence ------------------------------------
    ref = _train_run("none", args.steps)
    train_out = {"uncompressed_losses": ref,
                 "steps": args.steps, "tolerances": TRAIN_TOL}
    for comp in ("int8", "fp8"):
        try:
            losses = _train_run(comp, args.steps)
            div = _check_divergence(comp, losses, ref, TRAIN_TOL[comp])
            train_out[comp] = {
                "losses": losses,
                "max_relative_divergence": div,
                "within_tolerance": True,
            }
        except CorruptStats as e:
            # the refusal path: a blowup is published as a quarantined
            # row with the reason, never as a green number
            train_out[comp] = {"quarantined": True, "error": str(e)}

    backend = jax.default_backend()
    host_claim = (
        "CPU-simulated mesh: a ppermute is a memcpy, so walls carry no "
        "fabric signal.  The committed claim is correctness + wire "
        "volume: compressed == uncompressed within wire-dtype tolerance "
        "(tests/test_compression.py), the int8 wire <= 0.55x the bf16 "
        "baseline with scales included (comm-lint wire-volume ceiling, "
        "compressed targets in the default registry), and the train "
        "loss curves above within tolerance."
    )
    payload = {
        "harness": "scripts/bench_compression.py",
        "schema": "dlbb_bench_compress_v1",
        "grid": {
            "micro": f"allreduce(_q) + reducescatter(_q), {SIZE_LABEL} "
                     f"({SIZE_ELEMS} elems) x {RANKS} ranks, bf16 payload",
            "train": "h64 L2 full-attention DDP, dp=8, b8 x s32, "
                     f"{args.steps} steps",
        },
        "iterations_per_config": args.iters,
        "repetitions": args.reps,
        "methodology": (
            "settings interleaved within each repetition; medians of "
            "per-rep medians with min/max spread (PR-3 convention); "
            "bytes_on_wire is analytic (analysis/expectations."
            "op_wire_bytes, scale side channel included) — the same "
            "model comm-lint audits against the compiled HLO"
        ),
        "backend": backend,
        "topology": topology_record(),
        "jax_version": jax.__version__,
        "host_cpu_count": os.cpu_count(),
        "timestamp": time.time(),
        "micro_seconds_per_iteration": micro_out,
        "train_loss_divergence": train_out,
        "claim": host_claim if backend == "cpu" else (
            "chip run: walls are device-honest; compression shows as "
            "the _q rows beating their uncompressed baselines at equal "
            "logical payload"
        ),
        "chip": (
            {"status": "measured", "backend": backend}
            if backend != "cpu" else {
                "status": "pending_tunnel",
                "note": (
                    "chip perf row keyed for the next healthy tunnel "
                    "window: DLBB_TPU_TESTS=1 python "
                    "scripts/bench_compression.py --chip"
                ),
            }
        ),
    }
    atomic_write_text(json.dumps(payload, indent=1) + "\n",
                      Path(args.output))
    for name, _, operations in SETTINGS:
        row = micro_out[name]
        parts = [f"{op} {row[op]['median_s'] * 1e3:8.3f} ms"
                 for op in operations]
        print(f"[{name:13s}] " + " | ".join(parts))
    for comp in ("int8", "fp8"):
        r = train_out[comp]
        if r.get("quarantined"):
            print(f"[train/{comp}] QUARANTINED: {r['error']}")
        else:
            print(f"[train/{comp}] max divergence "
                  f"{r['max_relative_divergence']:.5f} "
                  f"(tol {TRAIN_TOL[comp]})")
    print(f"BENCH_compress.json -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
