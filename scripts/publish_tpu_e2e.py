#!/usr/bin/env python
"""Publish the real-TPU-chip E2E artifact set under ``results/e2e/``.

The CPU-simulated corpus (``scripts/publish_baselines.py``) covers the
collective sweeps; this script covers the part only the real chip can
measure — the E2E TP-forward benchmark (reference ``run_mpi.py`` semantics)
on the headline model configs.  Run WITHOUT ``--simulate`` on the TPU image:
the artifacts record the one v5e chip (world_size=1; multi-chip TP numbers
require a pod and are covered by the dryrun + simulated corpus instead).

Configs mirror ``bench.py``'s headline + extras set so the committed
artifacts substantiate the BENCH_r*.json lines:

- 1B  x {simplified, full, flash, dense}  @ S=512
- 7B  x {simplified, full, dense}         @ S=512
- 1B  x {full, dense}  @ S=1024  (flash auto-route pair)
- 1B  x flash @ {2048, 4096, 8192} + the dense@8192 infeasibility
  boundary artifact (long-context ladder, SURVEY §5.7)

Usage: python scripts/publish_tpu_e2e.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # _publish_common

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

CONFIGS = (
    ("1B", "simplified", 512),
    ("1B", "full", 512),
    ("1B", "flash", 512),
    ("1B", "dense", 512),   # pinned dense kernel: the un-routed baseline
    ("7B", "simplified", 512),
    ("7B", "full", 512),
    ("7B", "dense", 512),
    ("1B", "full", 1024),
    ("1B", "dense", 1024),
    # long-context ladder (SURVEY §5.7): O(S) flash memory vs the dense
    # path's [B,N,S,S] score tensor — dense is expected to RESOURCE_EXHAUST
    # by S=8192 (16 GiB scores); its failure is recorded, not hidden
    ("1B", "flash", 2048),
    ("1B", "flash", 4096),
    ("1B", "flash", 8192),
    ("1B", "dense", 8192),   # expected infeasible — see EXPECTED_FAIL_OK
)

# Configs whose MEMORY failure is itself the measurement (capability
# boundary): when the worker subprocess dies with a memory/compile-planning
# error signature, a *_infeasible.json boundary artifact is written and the
# run continues; any OTHER failure there still counts as a real failure.
EXPECTED_FAIL_OK = {("1B", "dense", 8192)}


BATCH_SIZE = 8  # every config in this script runs at B=8 (see _run_one)


def _experiment_name(size: str, attention: str, seq: int) -> str:
    return f"{size.lower()}_{attention}_s{seq}_world1"


def _artifact_name(size: str, attention: str, seq: int) -> str:
    """The ONE producer of the artifact basename — must match what
    ``run_e2e`` writes (``dlbb_tpu/bench/e2e.py``: ``xla_tpu_<name>.json``
    from the experiment name this script passes in)."""
    return f"xla_tpu_{_experiment_name(size, attention, seq)}"


def _boundary_reason(size: str, attention: str, seq: int) -> str:
    """Deterministic boundary reason computed from the config's own
    parameters (not hardcoded text): the dense path's [B, N, S, S] fp32
    score tensor vs the 16 GiB v5e HBM."""
    from dlbb_tpu.models.configs import MODEL_CONFIGS

    # the score-tensor arithmetic below is dense-path physics; a new
    # EXPECTED_FAIL_OK entry with another attention mode needs its own
    # reason rather than a factually wrong interpolation of this one
    assert attention == "dense", attention
    n_heads = MODEL_CONFIGS[size].num_heads
    score_gib = BATCH_SIZE * n_heads * seq * seq * 4 / 2**30
    return (
        f"{attention} attention materialises the [B, N, S, S] score "
        f"tensor ({score_gib:.0f} GiB fp32 at B={BATCH_SIZE}, "
        f"N={n_heads}, S={seq}) against the 16 GiB v5e HBM; the flash "
        f"artifact at the same shape is the measured alternative"
    )


def write_boundary_artifact(size: str, attention: str, seq: int,
                            output: str, exit_code: int,
                            observed_error: str) -> Path:
    """The deterministic boundary-artifact writer — the ONLY producer of
    ``*_infeasible.json`` files, so the committed corpus is reproducible
    from this script.  ``observed_error`` is the final error line from the
    worker's stderr (what actually happened), kept separate from the
    deterministic ``reason`` (why the boundary exists)."""
    boundary = {
        "experiment": {
            "name": _experiment_name(size, attention, seq),
        },
        "status": "infeasible",
        "reason": _boundary_reason(size, attention, seq),
        "observed_error": observed_error,
        "exit_code": exit_code,
    }
    out = Path(output)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{_artifact_name(size, attention, seq)}_infeasible.json"
    atomic_write_text(json.dumps(boundary, indent=2) + "\n", path)
    return path


def _run_one(size: str, attention: str, seq: int, iters: int,
             output: str) -> None:
    import jax

    devices = jax.devices()
    print(f"devices: {devices}", flush=True)
    if devices[0].platform not in ("tpu", "axon"):
        print("warning: not a TPU backend — artifacts will say so "
              f"(platform={devices[0].platform})", flush=True)

    from dlbb_tpu.bench.e2e import run_e2e

    config = {
        "experiment": {
            "name": _experiment_name(size, attention, seq),
        },
        "model": {"size": size, "attention": attention},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": BATCH_SIZE, "sequence_length": seq,
                  "seed": 42},
        "execution": {"warmup_iterations": 3,
                      "benchmark_iterations": iters},
    }
    run_e2e(config, output_dir=output)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--output", default=str(REPO / "results" / "e2e"))
    ap.add_argument("--only", default=None, metavar="SIZE,ATTENTION,SEQ",
                    help="run a single config in THIS process (the "
                         "per-config worker mode)")
    args = ap.parse_args()

    if args.only:
        size, attention, seq = args.only.split(",")
        _run_one(size, attention, int(seq), args.iters, args.output)
        return 0

    # One subprocess per config: a fresh process means a fresh HBM arena —
    # running the whole set in-process accumulates enough leftover
    # allocations that the 7B configs hit RESOURCE_EXHAUSTED on the 16 GB
    # chip after the three 1B models have run.
    from _publish_common import run_worker_matrix

    return run_worker_matrix(
        __file__,
        list(CONFIGS),
        only_str=lambda c: f"{c[0]},{c[1]},{c[2]}",
        artifact_name=lambda c: _artifact_name(*c),
        expected_fail_ok=EXPECTED_FAIL_OK,
        write_boundary=lambda c, out, rc, obs: write_boundary_artifact(
            *c, out, rc, obs),
        output=args.output,
        iters=args.iters,
        label=lambda c: f"{c[0]}/{c[1]}/s{c[2]}",
    )


if __name__ == "__main__":
    sys.exit(main())
