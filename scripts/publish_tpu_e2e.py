#!/usr/bin/env python
"""Publish the real-TPU-chip E2E artifact set under ``results/e2e/``.

The CPU-simulated corpus (``scripts/publish_baselines.py``) covers the
collective sweeps; this script covers the part only the real chip can
measure — the E2E TP-forward benchmark (reference ``run_mpi.py`` semantics)
on the headline model configs.  Run WITHOUT ``--simulate`` on the TPU image:
the artifacts record the one v5e chip (world_size=1; multi-chip TP numbers
require a pod and are covered by the dryrun + simulated corpus instead).

Configs mirror ``bench.py``'s headline + extras set so the committed
artifacts substantiate the BENCH_r*.json lines:

- 1B  x {simplified, full, flash}  @ S=512
- 7B  x {simplified, full}         @ S=512
- 1B  x {full, dense}              @ S=1024  (flash auto-route pair)

Usage: python scripts/publish_tpu_e2e.py [--iters N]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

CONFIGS = (
    ("1B", "simplified", 512),
    ("1B", "full", 512),
    ("1B", "flash", 512),
    ("7B", "simplified", 512),
    ("7B", "full", 512),
    ("1B", "full", 1024),
    ("1B", "dense", 1024),
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--output", default=str(REPO / "results" / "e2e"))
    args = ap.parse_args()

    import jax

    devices = jax.devices()
    print(f"devices: {devices}", flush=True)
    if devices[0].platform not in ("tpu", "axon"):
        print("warning: not a TPU backend — artifacts will say so "
              f"(platform={devices[0].platform})", flush=True)

    from dlbb_tpu.bench.e2e import run_e2e

    failures = []
    for size, attention, seq in CONFIGS:
        config = {
            "experiment": {
                "name": f"{size.lower()}_{attention}_s{seq}_world1",
            },
            "model": {"size": size, "attention": attention},
            "parallelism": {"world_size": 1, "data_parallel": 1},
            "input": {"batch_size": 8, "sequence_length": seq, "seed": 42},
            "execution": {"warmup_iterations": 3,
                          "benchmark_iterations": args.iters},
        }
        try:
            run_e2e(config, output_dir=args.output)
        except Exception as e:  # noqa: BLE001 — per-config resilience
            print(f"FAILED {size}/{attention}/s{seq}: {e}", flush=True)
            failures.append((size, attention, seq))
    if failures:
        print(f"{len(failures)} config(s) failed: {failures}", flush=True)
        return 1
    print(f"artifacts in {args.output}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
