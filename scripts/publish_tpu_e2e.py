#!/usr/bin/env python
"""Publish the real-TPU-chip E2E artifact set under ``results/e2e/``.

The CPU-simulated corpus (``scripts/publish_baselines.py``) covers the
collective sweeps; this script covers the part only the real chip can
measure — the E2E TP-forward benchmark (reference ``run_mpi.py`` semantics)
on the headline model configs.  Run WITHOUT ``--simulate`` on the TPU image:
the artifacts record the one v5e chip (world_size=1; multi-chip TP numbers
require a pod and are covered by the dryrun + simulated corpus instead).

Configs mirror ``bench.py``'s headline + extras set so the committed
artifacts substantiate the BENCH_r*.json lines:

- 1B  x {simplified, full, flash}  @ S=512
- 7B  x {simplified, full}         @ S=512
- 1B  x {full, dense}              @ S=1024  (flash auto-route pair)

Usage: python scripts/publish_tpu_e2e.py [--iters N]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

CONFIGS = (
    ("1B", "simplified", 512),
    ("1B", "full", 512),
    ("1B", "flash", 512),
    ("1B", "dense", 512),   # pinned dense kernel: the un-routed baseline
    ("7B", "simplified", 512),
    ("7B", "full", 512),
    ("7B", "dense", 512),
    ("1B", "full", 1024),
    ("1B", "dense", 1024),
)


def _run_one(size: str, attention: str, seq: int, iters: int,
             output: str) -> None:
    import jax

    devices = jax.devices()
    print(f"devices: {devices}", flush=True)
    if devices[0].platform not in ("tpu", "axon"):
        print("warning: not a TPU backend — artifacts will say so "
              f"(platform={devices[0].platform})", flush=True)

    from dlbb_tpu.bench.e2e import run_e2e

    config = {
        "experiment": {
            "name": f"{size.lower()}_{attention}_s{seq}_world1",
        },
        "model": {"size": size, "attention": attention},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": 8, "sequence_length": seq, "seed": 42},
        "execution": {"warmup_iterations": 3,
                      "benchmark_iterations": iters},
    }
    run_e2e(config, output_dir=output)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--output", default=str(REPO / "results" / "e2e"))
    ap.add_argument("--only", default=None, metavar="SIZE,ATTENTION,SEQ",
                    help="run a single config in THIS process (the "
                         "per-config worker mode)")
    args = ap.parse_args()

    if args.only:
        size, attention, seq = args.only.split(",")
        _run_one(size, attention, int(seq), args.iters, args.output)
        return 0

    # One subprocess per config: a fresh process means a fresh HBM arena —
    # running the whole set in-process accumulates enough leftover
    # allocations that the 7B configs hit RESOURCE_EXHAUSTED on the 16 GB
    # chip after the three 1B models have run.
    import subprocess

    failures = []
    for size, attention, seq in CONFIGS:
        cmd = [sys.executable, __file__, "--iters", str(args.iters),
               "--output", args.output, "--only",
               f"{size},{attention},{seq}"]
        r = subprocess.run(cmd)
        if r.returncode != 0:
            print(f"FAILED {size}/{attention}/s{seq} "
                  f"(exit {r.returncode})", flush=True)
            failures.append((size, attention, seq))
    if failures:
        print(f"{len(failures)} config(s) failed: {failures}", flush=True)
        return 1
    print(f"artifacts in {args.output}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
