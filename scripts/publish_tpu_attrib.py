#!/usr/bin/env python
"""Attribute the train-MFU gap on the real chip (VERDICT r4 #2).

Two modes:

- default (chip required): run the headline train config
  (1B, adam+bf16 moments, dots remat) for a few steps inside
  ``jax.profiler.trace``, parse the xplane with ``jax.profiler.
  ProfileData``, and write a per-op device-time summary to
  ``results/traces/`` — the committed, greppable form of "what the chip
  spent the step on" (the raw xplane stays uncommitted; the summary is
  the artifact).
- ``--decompose`` (pure file IO, no chip): join the committed forward
  (``results/e2e/xla_tpu_1b_full_s512_world1.json``) and train
  (``results/train/train_ddp_1B_train_chip_{sgd,adam_bf16m}_dots*.json``)
  artifacts into the forward/backward/optimizer decomposition the docs
  quote: backward time = sgd step - forward (SGD's axpy update is
  single-digit ms), optimizer delta = adam step - sgd step.

Reference anchor: the training capability at ``test/ccl.py:59-117``;
peak math in ``BASELINE.md``.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
import time
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

E2E_FWD = {
    (8, 512): "results/e2e/xla_tpu_1b_full_s512_world1.json",
    (8, 1024): "results/e2e/xla_tpu_1b_full_s1024_world1.json",
}
# every shape the SGD ladder measures; shapes without a matching-batch
# e2e forward artifact (the e2e publisher runs at B=8 only) still get a
# decomposition row carrying the train-side rates, with the
# forward/backward split left null rather than silently dropped
LADDER_SHAPES = ((8, 512), (16, 512), (32, 512), (8, 1024), (16, 1024))
TRAIN_ART = "results/train/train_ddp_1B_train_chip_{suffix}.json"


def parse_xplane(trace_dir: str, top_k: int = 25) -> dict:
    """Aggregate device-plane op durations from the newest xplane in
    ``trace_dir``; falls back to host planes (recorded as such) when the
    backend emitted no device plane."""
    from jax.profiler import ProfileData

    files = sorted(glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True))
    if not files:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    pd = ProfileData.from_file(files[-1])

    planes = {}
    for plane in pd.planes:
        by_op: dict[str, float] = {}
        events = 0
        for line in plane.lines:
            for ev in line.events:
                dur = getattr(ev, "duration_ns", None) or 0.0
                by_op[ev.name] = by_op.get(ev.name, 0.0) + float(dur)
                events += 1
        if events:
            planes[plane.name] = {"events": events, "by_op": by_op}

    device_planes = {
        n: p for n, p in planes.items()
        if "TPU" in n.upper() or "/device:" in n
    }
    chosen = device_planes or planes
    summary = {}
    for name, p in chosen.items():
        total = sum(p["by_op"].values())
        top = sorted(p["by_op"].items(), key=lambda kv: -kv[1])[:top_k]
        summary[name] = {
            "total_ms": round(total / 1e6, 3),
            "events": p["events"],
            "top_ops_ms": [
                {"op": op, "ms": round(ns / 1e6, 3),
                 "pct": round(100 * ns / total, 1) if total else None}
                for op, ns in top
            ],
        }
    return {
        "xplane_file": files[-1],
        "device_plane_found": bool(device_planes),
        "planes": summary,
    }


def run_traced(batch: int, seq: int, steps: int, output: str) -> Path:
    import jax

    print(f"devices: {jax.devices()}", flush=True)
    from dlbb_tpu.train.loop import run_train

    trace_dir = f"/tmp/dlbb_attrib_trace_b{batch}_s{seq}"
    config = {
        "experiment": {"name": f"1B_attrib_b{batch}_s{seq}"},
        "model": {"size": "1B", "attention": "full", "remat": True,
                  "remat_policy": "dots"},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": batch, "sequence_length": seq, "seed": 42},
        # short: the trace is the product, not the timing statistics
        "execution": {"warmup_iterations": 2, "benchmark_iterations": steps},
        "training": {"learning_rate": 1e-4, "optimizer": "adam",
                     "moments_dtype": "bfloat16"},
    }
    from dlbb_tpu.utils.profiling import maybe_trace

    with maybe_trace(trace_dir):
        result = run_train(config, zero_stage=0, output_dir=None)

    summary = parse_xplane(trace_dir)
    summary["config"] = {"model": "1B", "batch": batch, "seq": seq,
                         "optimizer": "adam_bf16m", "remat": "dots"}
    summary["step_time_mean_s"] = result["step_time"]["mean"]
    summary["achieved_tflops_per_second"] = (
        result["achieved_tflops_per_second"])
    summary["timestamp"] = time.time()
    out = Path(output)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"train_attrib_trace_b{batch}_s{seq}.json"
    atomic_write_text(json.dumps(summary, indent=2) + "\n", path)
    print(f"trace summary -> {path}", flush=True)
    return path


def decompose(output: str) -> Path:
    """Forward/backward/optimizer split from committed chip artifacts."""

    def load(p):
        f = REPO / p
        return json.loads(f.read_text()) if f.is_file() else None

    rows = []
    for b, s in LADDER_SHAPES:
        # canonical-shape rungs carry no shape suffix; the Adam shape
        # rungs are all measured-infeasible on the 16 GiB chip (their
        # boundary artifacts ARE the ladder points), so off-canonical
        # shapes decompose from the stateless-SGD ladder (sgd_dots_*)
        # with the optimizer delta only where Adam fits
        if (b, s) == (8, 512):
            sgd = load(TRAIN_ART.format(suffix="sgd_remat_dots"))
            adam = load(TRAIN_ART.format(suffix="adam_bf16m_dots"))
        else:
            sgd = load(TRAIN_ART.format(suffix=f"sgd_dots_b{b}_s{s}"))
            adam = load(TRAIN_ART.format(
                suffix=f"adam_bf16m_dots_b{b}_s{s}"))
        if adam is not None and "status" in adam:
            adam = None  # boundary artifact, not a measurement
        if sgd is None or "status" in sgd:
            continue
        sgd_s = sgd["step_time"]["mean"]
        row = {
            "batch": b, "seq": s,
            "sgd_step_s": round(sgd_s, 5),
            "sgd_train_tflops": round(
                sgd["achieved_tflops_per_second"], 1),
        }
        fwd = load(E2E_FWD.get((b, s), ""))
        if fwd is not None:
            fwd_s = fwd["forward_time"]["mean"]
            flops_fwd = fwd["model_flops_per_forward"]
            # backward = sgd step - forward: SGD's update is a single
            # axpy over the params (~2.6 GB HBM traffic, single-digit
            # ms) so the residual is backward + dispatch
            bwd_s = sgd_s - fwd_s
            row.update({
                "forward_s": round(fwd_s, 5),
                "forward_tflops": round(flops_fwd / fwd_s / 1e12, 1),
                "backward_s": round(bwd_s, 5),
                # backward executes 2x the forward FLOPs
                "backward_tflops": round(2 * flops_fwd / bwd_s / 1e12, 1),
            })
        else:
            # no matching-batch forward artifact (e2e publisher is B=8):
            # the train-side rate still lands; the split stays null
            row.update({"forward_s": None, "forward_tflops": None,
                        "backward_s": None, "backward_tflops": None})
        if adam is not None:
            adam_s = adam["step_time"]["mean"]
            row.update({
                "adam_step_s": round(adam_s, 5),
                "train_tflops": round(
                    adam["achieved_tflops_per_second"], 1),
                "optimizer_delta_s": round(adam_s - sgd_s, 5),
                "optimizer_pct_of_step": round(
                    100 * (adam_s - sgd_s) / adam_s, 1),
            })
        rows.append(row)

    out = Path(output)
    out.mkdir(parents=True, exist_ok=True)
    path = out / "train_attrib_decomposition.json"
    atomic_write_text(json.dumps(
        {"rows": rows,
         "method": "backward_s = sgd_dots step - e2e forward; "
                   "optimizer_delta_s = adam_bf16m_dots step - sgd_dots "
                   "step; all chip-measured chained timings",
         "timestamp": time.time()}, indent=2) + "\n", path)
    print(f"decomposition ({len(rows)} rows) -> {path}", flush=True)
    return path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--decompose", action="store_true",
                    help="artifact-join decomposition only (no chip)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--output", default=str(REPO / "results" / "traces"))
    args = ap.parse_args()
    if args.decompose:
        decompose(str(REPO / "results" / "train"))
        return 0
    run_traced(args.batch, args.seq, args.steps, args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
