#!/usr/bin/env python
"""Fleet fault-tolerance evidence: the measured cost of a failover.

Measures the replica-level fleet supervisor (docs/fleet.md) through its
real serving path on the CPU-simulated 8-rank mesh and writes
``BENCH_fleet.json`` at the repo root:

- **single** — one engine on one replica-sized (dp=2 x tp=2) mesh: the
  token-identity oracle and the clean-TTFT reference.
- **fleet_clean** — the same trace through a 2-replica fleet with no
  faults: what supervision itself costs (routing, heartbeats, the
  event pump).
- **fleet_kill** — the same trace with ``serve-replica-kill`` fired
  mid-trace: one replica fenced, its residents re-prefilled on the
  survivor.  The published headline is the **failover TTFT penalty**
  — mean arrival-to-first-token of failed-over requests minus the
  clean requests' in the SAME run (the fleet report's
  ``failover_ttft_penalty_s``) — plus the goodput retained vs the
  unfaulted fleet.

**Token-identity gate**: greedy tokens depend only on (params seed,
request), so every fleet run — clean AND killed — must reproduce the
single-engine oracle's completed-token sequences exactly before any
number is published; a mismatch aborts the bench.

Methodology follows ``scripts/bench_serving.py``: settings are
INTERLEAVED within each repetition so host drift cancels, and medians
of per-rep values are reported with min/max spread.  Each rep builds
fresh engines (a fleet run consumes its replicas), so compile cost is
excluded by measuring goodput from the report's own wall, not ours.

Usage: python scripts/bench_fleet.py [--requests N] [--reps R]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402
from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

force_cpu_simulation(8)

import jax  # noqa: E402

from dlbb_tpu.serve.bench import run_serving  # noqa: E402
from dlbb_tpu.serve.fleet import run_fleet  # noqa: E402
from dlbb_tpu.serve.traffic import generate_trace  # noqa: E402
from dlbb_tpu.stats.serving_report import write_fleet_report  # noqa: E402
from dlbb_tpu.utils.simulate import topology_record  # noqa: E402

BENCH_MODEL = dict(hidden_size=64, num_layers=2, num_heads=4,
                   num_kv_heads=4, ffn_intermediate=128, dtype="float32",
                   attention="full")
SERVE = dict(max_batch=8, block_size=8, max_seq=64, queue_capacity=64,
             hbm_budget_gb=None)
KILL_PLAN = "serve-replica-kill:@8"


def _cfg(name: str) -> dict:
    # per-replica parallelism: 2 replicas x (dp=2 x tp=2) on 8 devices;
    # the single-engine oracle uses the SAME (dp=2 x tp=2) on 4 devices
    return {"experiment": {"name": name}, "model": dict(BENCH_MODEL),
            "parallelism": {"data_parallel": 2, "world_size": 2},
            "serving": dict(SERVE), "fleet": {"replicas": 2}}


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _spread(vals) -> dict:
    return {"median": _median(vals), "min": min(vals), "max": max(vals),
            "reps": list(vals)}


def _gate_tokens(got: dict, oracle: dict, what: str) -> None:
    if got != oracle:
        bad = [r for r in oracle if got.get(r) != oracle[r]]
        raise SystemExit(
            f"token-identity gate FAILED ({what}): requests {bad} "
            "diverged from the single-engine oracle — refusing to "
            "publish fault-tolerance numbers for a wrong result")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per setting (default 3)")
    ap.add_argument("--output", default=str(REPO / "BENCH_fleet.json"))
    args = ap.parse_args()

    trace = generate_trace("poisson", args.requests, seed=5, rate=60.0,
                           prompt_range=(4, 12), output_range=(4, 8))
    single_cfg = _cfg("single")
    del single_cfg["fleet"]

    per_rep: dict[str, list[dict]] = {
        "single": [], "fleet_clean": [], "fleet_kill": []}
    penalties: list[float] = []
    failovers: list[int] = []
    for rep_i in range(args.reps):
        runs = {
            "single": run_serving(single_cfg, trace, verbose=False,
                                  devices=jax.devices()[:4],
                                  journal=False, capture_tokens=True),
            "fleet_clean": run_fleet(_cfg("clean"), trace, verbose=False,
                                     journal=False, capture_tokens=True),
            "fleet_kill": run_fleet(_cfg("kill"), trace, verbose=False,
                                    journal=False, capture_tokens=True,
                                    fault_plan=KILL_PLAN),
        }
        oracle = runs["single"]["completed_tokens"]
        _gate_tokens(runs["fleet_clean"]["completed_tokens"], oracle,
                     f"fleet_clean rep {rep_i}")
        _gate_tokens(runs["fleet_kill"]["completed_tokens"], oracle,
                     f"fleet_kill rep {rep_i}")
        kill = runs["fleet_kill"]
        if not any(r["fence_reason"] == "replica-killed"
                   for r in kill["replicas"]):
            raise SystemExit("kill plan never fenced a replica — the "
                             "penalty column would measure nothing")
        if kill["failover_ttft_penalty_s"] is None:
            raise SystemExit("kill rep produced no failover — cannot "
                             "measure the TTFT penalty")
        penalties.append(kill["failover_ttft_penalty_s"])
        failovers.append(kill["failovers"]["total"])
        for name, r in runs.items():
            out = r["requests"]["outcomes"]
            if any(v != "completed" for v in out.values()):
                raise SystemExit(f"{name} rep {rep_i}: not every request "
                                 f"completed: {out}")
            per_rep[name].append({
                "tok_s": r["goodput_tokens_per_s"],
                "ttft_p50_s": r["ttft"]["median"],
                "ttft_p99_s": r["ttft"]["p99"],
                "wall_s": r["wall_seconds"],
            })

    settings_out = {}
    for name, reps in per_rep.items():
        settings_out[name] = {
            "goodput_tokens_per_s": _spread([r["tok_s"] for r in reps]),
            "ttft_p50_ms": round(
                _median([r["ttft_p50_s"] for r in reps]) * 1e3, 3),
            "ttft_p99_ms": round(
                _median([r["ttft_p99_s"] for r in reps]) * 1e3, 3),
            "wall_seconds": round(
                _median([r["wall_s"] for r in reps]), 3),
            "token_identical": True,
        }
    settings_out["fleet_kill"]["failovers"] = _spread(failovers)
    clean_med = settings_out["fleet_clean"][
        "goodput_tokens_per_s"]["median"]
    kill_med = settings_out["fleet_kill"][
        "goodput_tokens_per_s"]["median"]

    payload = {
        "harness": "scripts/bench_fleet.py",
        "schema": "dlbb_bench_fleet_v1",
        "model": dict(BENCH_MODEL),
        "serving": dict(SERVE),
        "fleet": {"replicas": 2,
                  "parallelism_per_replica": {"dp": 2, "tp": 2}},
        "trace": {"kind": trace.kind, "requests": len(trace),
                  "seed": trace.seed, "params": dict(trace.params)},
        "repetitions": args.reps,
        "fault_plan": KILL_PLAN,
        "methodology": (
            "identical seeded trace through every setting, settings "
            "interleaved within each repetition; medians with min/max "
            "spread; token-identity gate (fleet == single-engine "
            "oracle, clean AND killed) enforced every rep before "
            "publishing; the TTFT penalty is failed-over minus clean "
            "requests WITHIN the kill run, so queueing drift between "
            "runs cancels"
        ),
        "backend": jax.default_backend(),
        "topology": topology_record(),
        "jax_version": jax.__version__,
        "host_cpu_count": os.cpu_count(),
        "timestamp": time.time(),
        "settings": settings_out,
        "failover": {
            "ttft_penalty_ms": _spread(
                [round(p * 1e3, 3) for p in penalties]),
            "failovers_per_run": _spread(failovers),
            "goodput_retained_vs_clean_fleet": round(
                kill_med / clean_med, 3),
        },
        "claim": (
            "CPU-simulated mesh: the penalty prices the host-side "
            "failover path honestly (fence, re-route, re-prefill on "
            "the survivor) — on chip the re-prefill grows with real "
            "prefill cost while fence + re-route stay host-bound."
        ),
    }
    atomic_write_text(json.dumps(payload, indent=1) + "\n",
                      Path(args.output))
    write_fleet_report(Path(args.output), REPO / "stats" / "serving")
    for name, s in settings_out.items():
        tps = s["goodput_tokens_per_s"]
        print(f"[{name:12s}] {tps['median']:8.1f} tok/s "
              f"({tps['min']:.1f}..{tps['max']:.1f})  "
              f"TTFT p50 {s['ttft_p50_ms']:.1f}ms")
    pen = payload["failover"]["ttft_penalty_ms"]
    print(f"[failover] TTFT penalty {pen['median']:.1f}ms "
          f"({pen['min']:.1f}..{pen['max']:.1f}) over "
          f"{_median(failovers)} failover(s)/run; goodput retained "
          f"{payload['failover']['goodput_retained_vs_clean_fleet']:.2f}x"
          " vs unfaulted fleet")
    print(f"BENCH_fleet.json -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
