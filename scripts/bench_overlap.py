#!/usr/bin/env python
"""Fused-vs-decomposed evidence for the overlapped collective matmul.

Measures the three TP schedules (``off``/fused, ``ring``, ``bidir`` —
docs/overlap.md) through the framework's own timed regions and writes
``BENCH_overlap.json`` at the repo root:

- **micro** — the two collective-matmul ops (``ag_matmul`` /
  ``matmul_rs``) swept through the PR-3 engine (work-unit dedup, payload
  avals, measurement gate) under the ``default`` / ``overlap_ring`` /
  ``overlap_bidir`` variants;
- **e2e** — the TP transformer forward (``bench/e2e.py``) under
  ``model.tp_overlap`` off/ring/bidir.

Methodology follows ``scripts/bench_sweep_engine.py``: settings are
INTERLEAVED within each repetition so host drift cancels across modes,
and medians-of-medians are reported with min/max spread.

On this image the mesh is CPU-simulated: every device is a host thread
and a ppermute is a memcpy, so wall clocks say nothing about ICI overlap
— the committed artifact's claim is **correctness + schedule shape**
(equivalence is pinned by tests/test_collective_matmul.py, the permute
chain by the comm-lint HLO audit), with the chip perf row keyed
``pending`` for the next healthy tunnel window
(``DLBB_TPU_TESTS=1 python scripts/bench_overlap.py --chip``).

Usage: python scripts/bench_overlap.py [--iters N] [--reps R] [--chip]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

CHIP = "--chip" in sys.argv[1:]
if not CHIP:
    from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

    force_cpu_simulation(8)

import jax  # noqa: E402

from dlbb_tpu.bench.e2e import run_e2e  # noqa: E402
from dlbb_tpu.bench.runner import Sweep3D, run_sweep  # noqa: E402

SCHEDULES = ("off", "ring", "bidir")
# micro-op variant per schedule (the fused baseline is the default variant)
VARIANT_OF = {"off": "default", "ring": "overlap_ring",
              "bidir": "overlap_bidir"}

# LLM-shaped micro grid: S and H divide the 8-rank ring; small enough
# that the simulated mesh measures in seconds, big enough that the
# matmul dominates trace overhead
MICRO_GRID = dict(batch_sizes=(2,), seq_lengths=(256,), hidden_dims=(256,))

E2E_MODEL = {
    "hidden_size": 256,
    "num_layers": 2,
    "num_heads": 8,
    "ffn_intermediate": 1024,
    "attention": "full",
    "dtype": "float32",
}


def _micro_run(schedule: str, work: Path, iters: int) -> dict:
    out = work / f"micro_{schedule}_{time.monotonic_ns()}"
    sweep = Sweep3D(
        implementation="bench_overlap",
        variant=VARIANT_OF[schedule],
        operations=("ag_matmul", "matmul_rs"),
        rank_counts=(8,),
        dtype="float32",
        warmup_iterations=2,
        measurement_iterations=iters,
        output_dir=str(out),
        compile_cache="off",
        **MICRO_GRID,
    )
    files = run_sweep(sweep, verbose=False)
    medians = {}
    for f in files:
        d = json.loads(Path(f).read_text())
        flat = sorted(t for row in d["timings"] for t in row)
        medians[d["operation"]] = flat[len(flat) // 2]
    return medians


def _e2e_run(schedule: str, iters: int) -> float:
    config = {
        "experiment": {"name": f"overlap_{schedule}"},
        "model": dict(E2E_MODEL, tp_overlap=schedule),
        "parallelism": {"world_size": 8, "data_parallel": 1},
        "input": {"batch_size": 2, "sequence_length": 256, "seed": 42},
        "execution": {"warmup_iterations": 2,
                      "benchmark_iterations": iters},
    }
    result = run_e2e(config, verbose=False)
    return float(result["forward_time"]["median"])


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _spread(vals):
    return {
        "median_s": _median(vals),
        "min_s": min(vals),
        "max_s": max(vals),
        "repetitions": len(vals),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20,
                    help="measured iterations per config (default 20)")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per schedule (default 3)")
    ap.add_argument("--chip", action="store_true",
                    help="run on the real TPU chip instead of the "
                         "simulated mesh (fills the chip row)")
    ap.add_argument("--output", default=str(REPO / "BENCH_overlap.json"))
    args = ap.parse_args()

    work = Path(tempfile.mkdtemp(prefix="bench_overlap_"))
    micro: dict[str, list[dict]] = {s: [] for s in SCHEDULES}
    e2e: dict[str, list[float]] = {s: [] for s in SCHEDULES}
    try:
        # absorb process one-time costs so the first measured schedule
        # isn't billed for imports/first-dispatch
        _micro_run("off", work, 3)
        for _ in range(args.reps):
            # interleave schedules within each repetition (host-drift
            # cancellation, same convention as bench_sweep_engine.py)
            for s in SCHEDULES:
                micro[s].append(_micro_run(s, work, args.iters))
            for s in SCHEDULES:
                e2e[s].append(_e2e_run(s, args.iters))
    finally:
        shutil.rmtree(work, ignore_errors=True)

    backend = jax.default_backend()
    micro_out = {
        s: {
            op: _spread([rep[op] for rep in micro[s]])
            for op in ("ag_matmul", "matmul_rs")
        }
        for s in SCHEDULES
    }
    e2e_out = {s: _spread(e2e[s]) for s in SCHEDULES}

    host_claim = (
        "CPU-simulated mesh: devices are host threads and ppermute is a "
        "memcpy, so these walls carry no ICI-overlap signal.  The "
        "committed claim is correctness + schedule shape: ring/bidir == "
        "fused numerically (tests/test_collective_matmul.py) and the "
        "compiled programs are pure collective-permute chains with no "
        "surviving fused collective (comm-lint HLO audit, overlap "
        "targets in the default registry)."
    )
    payload = {
        "harness": "scripts/bench_overlap.py",
        "schema": "dlbb_bench_overlap_v1",
        "grid": {
            "micro": "ag_matmul + matmul_rs, B2 x S256 x H256, 8 ranks",
            "e2e": "h256 L2 full-attention forward, tp=8, B2 x S256",
        },
        "iterations_per_config": args.iters,
        "repetitions": args.reps,
        "methodology": (
            "schedules interleaved within each repetition; medians of "
            "per-rep medians with min/max spread (PR-3 convention, "
            "scripts/bench_sweep_engine.py)"
        ),
        "backend": backend,
        "jax_version": jax.__version__,
        "host_cpu_count": os.cpu_count(),
        "timestamp": time.time(),
        "micro_seconds_per_iteration": micro_out,
        "e2e_forward_seconds": e2e_out,
        "claim": host_claim if backend == "cpu" else (
            "chip run: walls are device-honest; overlap shows as "
            "ring/bidir e2e forward beating off"
        ),
        "chip": (
            {"status": "measured", "backend": backend}
            if backend != "cpu" else {
                "status": "pending_tunnel",
                "note": (
                    "chip perf row keyed for the next healthy tunnel "
                    "window: DLBB_TPU_TESTS=1 python "
                    "scripts/bench_overlap.py --chip"
                ),
            }
        ),
    }
    atomic_write_text(json.dumps(payload, indent=1) + "\n",
                      Path(args.output))
    for s in SCHEDULES:
        print(f"[{s:5s}] e2e fwd median {e2e_out[s]['median_s']*1e3:8.2f} ms"
              f" | ag_matmul {micro_out[s]['ag_matmul']['median_s']*1e3:7.3f}"
              f" ms | matmul_rs"
              f" {micro_out[s]['matmul_rs']['median_s']*1e3:7.3f} ms")
    print(f"BENCH_overlap.json -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
