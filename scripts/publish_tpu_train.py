#!/usr/bin/env python
"""Publish the real-TPU-chip TRAIN artifact set under ``results/train/``.

The train-side analogue of ``publish_tpu_e2e.py`` — and the provenance
record for every ``*_chip_*`` train artifact: every committed
``results/train/train_ddp_1B_train_chip_*.json`` has a matching suffix in
``CONFIGS`` (round 3's ad-hoc ``sgd`` artifact was superseded by the
``sgd_remat_full`` config, which measures the identical configuration
with provenance).  Covers the two round-4 asks:

- **the reference's optimizer on the chip**: the reference trains only
  with Adam (``/root/reference/test/ccl.py:74-117``,
  ``test/ds_mpi_test.py:16-24``).  Both the VERBATIM fp32-moments Adam
  (fits since the chained-timing carry-donation fix halved resident
  TrainState HBM, ``utils/timing.py``) and the memory-reduced
  ``training.moments_dtype: bfloat16`` variant (numerics vs fp32 Adam
  asserted in ``tests/test_optim.py``) are measured.
- **the remat-policy ladder**: remat off / "dots" (save matmul outputs) /
  "full" (save nothing) at the same 1B/b8/s512 shape, isolating the
  memory/recompute trade the round-3 117 TFLOP/s number silently included
  (every layer full-remat).  Artifacts record MODEL-flops MFU and the
  device-work ``*_incl_recompute`` rate.

Usage: python scripts/publish_tpu_train.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # _publish_common

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

# (name_suffix, training overrides, model overrides, input overrides)
# input overrides {} = the canonical BATCH_SIZE/SEQ_LEN shape.
_DOTS_ADAM = {"optimizer": "adam", "moments_dtype": "bfloat16"}
_DOTS_MODEL = {"remat": True, "remat_policy": "dots"}
CONFIGS: tuple[tuple[str, dict, dict, dict], ...] = (
    # reference-parity optimizer, memory-reduced variant
    ("adam_bf16m",
     {"optimizer": "adam", "moments_dtype": "bfloat16"},
     {"remat": True, "remat_policy": "full"}, {}),
    # the reference's optimizer VERBATIM (fp32 moments) — fits since the
    # chained-timing carry-donation fix
    ("adam_fp32m",
     {"optimizer": "adam"},
     {"remat": True, "remat_policy": "full"}, {}),
    # remat-policy ladder at fixed optimizer (stateless SGD isolates the
    # activation-memory axis from optimizer-state memory)
    ("sgd_remat_off", {"optimizer": "sgd"}, {"remat": False}, {}),
    ("sgd_remat_dots", {"optimizer": "sgd"},
     {"remat": True, "remat_policy": "dots"}, {}),
    ("sgd_remat_full", {"optimizer": "sgd"},
     {"remat": True, "remat_policy": "full"}, {}),
    # best-policy headline at the reference optimizer config
    ("adam_bf16m_dots", _DOTS_ADAM, _DOTS_MODEL, {}),
    # the TPU-idiomatic large-model optimizer (factored second moments)
    ("adafactor", {"optimizer": "adafactor"},
     {"remat": True, "remat_policy": "full"}, {}),
    # shape ladder at the headline config (VERDICT r4 #2): does a bigger
    # batch/longer sequence lift the ~121 TFLOP/s backward rate toward the
    # 158.6 forward rate?  b8/s512 is the adam_bf16m_dots row above.
    ("adam_bf16m_dots_b16_s512", _DOTS_ADAM, _DOTS_MODEL,
     {"batch_size": 16}),
    ("adam_bf16m_dots_b32_s512", _DOTS_ADAM, _DOTS_MODEL,
     {"batch_size": 32}),
    ("adam_bf16m_dots_b8_s1024", _DOTS_ADAM, _DOTS_MODEL,
     {"sequence_length": 1024}),
    ("adam_bf16m_dots_b16_s1024", _DOTS_ADAM, _DOTS_MODEL,
     {"batch_size": 16, "sequence_length": 1024}),
    ("adam_bf16m_dots_b32_s1024", _DOTS_ADAM, _DOTS_MODEL,
     {"batch_size": 32, "sequence_length": 1024}),
    # the Adam shape rungs above all OOM on the 16 GiB chip (b16/s512
    # misses by just 619 MB — Adam's two 1.3B-param bf16 moment buffers
    # are ~5.2 GB of it), so the measurable shape axis runs on stateless
    # SGD: sgd_step - forward isolates the backward rate either way, and
    # dropping the moments frees the HBM the bigger activations need.
    ("sgd_dots_b16_s512", {"optimizer": "sgd"}, _DOTS_MODEL,
     {"batch_size": 16}),
    ("sgd_dots_b32_s512", {"optimizer": "sgd"}, _DOTS_MODEL,
     {"batch_size": 32}),
    ("sgd_dots_b8_s1024", {"optimizer": "sgd"}, _DOTS_MODEL,
     {"sequence_length": 1024}),
    ("sgd_dots_b16_s1024", {"optimizer": "sgd"}, _DOTS_MODEL,
     {"batch_size": 16, "sequence_length": 1024}),
)

# sgd_remat_off: the no-remat rung of the ladder — measured OOM at compile
# (19.30G program HBM vs 15.75G usable: 24 layers x [B,S,ffn] bf16
# activations stored for backward); its failure IS the ladder's data point
# for "remat off", quantifying what remat buys.
#
# adam_fp32m is NOT here: it OOMed only while the chained timing loop kept
# two TrainState copies resident; with the carry-donation fix
# (utils/timing.py::time_fn_chained) the reference's verbatim optimizer
# measures cleanly (results/train/train_ddp_1B_train_chip_adam_fp32m.json),
# so a failure there is a real regression again.
#
# The big shape-ladder rungs may OOM (dots-remat still stores the saved
# dot outputs per layer, which scale with B x S): if they do, the boundary
# artifact IS the ladder's data point for that shape.
EXPECTED_FAIL_OK = {"sgd_remat_off",
                    # the Adam shape rungs OOM on the chip — four are
                    # measured boundaries (b16/s512 needs 16.35G of
                    # 15.75G; the bf16 moment buffers are ~5.2 GB of
                    # the footprint); b8_s1024 is expected-fail by the
                    # same arithmetic but still pending measurement
                    "adam_bf16m_dots_b16_s512",
                    "adam_bf16m_dots_b32_s512",
                    "adam_bf16m_dots_b8_s1024",
                    "adam_bf16m_dots_b16_s1024",
                    "adam_bf16m_dots_b32_s1024",
                    # the stateless-SGD ladder's own biggest shapes
                    "sgd_dots_b32_s512",
                    "sgd_dots_b16_s1024"}

BATCH_SIZE = 8
SEQ_LEN = 512


def _experiment_name(suffix: str) -> str:
    return f"1B_train_chip_{suffix}"


def _artifact_name(suffix: str) -> str:
    """Must match ``run_train``'s ``train_<mode>_<name>.json`` (zero stage 0
    = mode "ddp", ``dlbb_tpu/train/loop.py``)."""
    return f"train_ddp_{_experiment_name(suffix)}"


def _boundary_reason(suffix: str) -> str:
    from dlbb_tpu.models.configs import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["1B"]
    if suffix == "sgd_remat_off":
        # stored-for-backward activation footprint is dominated by the
        # per-layer [B, S, ffn] intermediates (bf16)
        act_gib = (cfg.num_layers * BATCH_SIZE * SEQ_LEN
                   * cfg.ffn_intermediate * 2 / 2**30)
        return (
            f"without remat every layer's forward activations stay resident "
            f"for the backward pass ({act_gib:.1f} GiB PER stacked "
            f"[L,B,S,ffn] bf16 intermediate at L={cfg.num_layers}, "
            f"B={BATCH_SIZE}, S={SEQ_LEN}, ffn={cfg.ffn_intermediate}, and "
            f"XLA keeps several plus the fp32 hidden streams: 19.30G program "
            f"HBM vs 15.75G usable at compile) — the measured remat ladder "
            f"points are the dots/full artifacts"
        )
    # shape-ladder rungs: the dots policy still saves every dot output —
    # per layer the stacked [L,B,S,ffn]+[L,B,S,H] bf16 saves scale
    # linearly with B x S and the 16 GiB chip runs out
    b, s = _ladder_shape(suffix)
    saved_gib = (cfg.num_layers * b * s
                 * (cfg.ffn_intermediate + cfg.hidden_size) * 2 / 2**30)
    state = ("params + Adam state (~5.2 GB of bf16 moments alone)"
             if suffix.startswith("adam") else "params + gradients")
    return (
        f"dots-remat saved activations scale with B x S (~{saved_gib:.1f} "
        f"GiB of stacked bf16 dot outputs at L={cfg.num_layers}, B={b}, "
        f"S={s}) on the 16 GiB (15.75 usable) v5e chip alongside {state} "
        f"— this shape rung is infeasible single-chip; the "
        f"measured ladder points are the smaller shapes"
    )


def _ladder_shape(suffix: str) -> tuple[int, int]:
    """(batch, seq) for a shape-ladder suffix, else the canonical shape."""
    b, s = BATCH_SIZE, SEQ_LEN
    for part in suffix.split("_"):
        if part.startswith("b") and part[1:].isdigit():
            b = int(part[1:])
        elif part.startswith("s") and part[1:].isdigit():
            s = int(part[1:])
    return b, s


def write_boundary_artifact(suffix: str, output: str, exit_code: int,
                            observed_error: str) -> Path:
    boundary = {
        "experiment": {"name": _experiment_name(suffix)},
        "status": "infeasible",
        "reason": _boundary_reason(suffix),
        "observed_error": observed_error,
        "exit_code": exit_code,
    }
    out = Path(output)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{_artifact_name(suffix)}_infeasible.json"
    atomic_write_text(json.dumps(boundary, indent=2) + "\n", path)
    return path


def _run_one(suffix: str, iters: int, output: str) -> None:
    # validate the suffix BEFORE any JAX/runtime init: a typo must fail in
    # milliseconds, not after grabbing the chip
    match = [(t, m, i) for s, t, m, i in CONFIGS if s == suffix]
    if not match:
        raise SystemExit(
            f"unknown config {suffix!r}; known: "
            f"{[s for s, _, _, _ in CONFIGS]}"
        )
    training, model_over, input_over = match[0]

    import jax

    print(f"devices: {jax.devices()}", flush=True)

    from dlbb_tpu.train.loop import run_train
    config = {
        "experiment": {"name": _experiment_name(suffix)},
        "model": {"size": "1B", "attention": "full", **model_over},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": BATCH_SIZE, "sequence_length": SEQ_LEN,
                  "seed": 42, **input_over},
        "execution": {"warmup_iterations": 2,
                      "benchmark_iterations": iters},
        "training": {"learning_rate": 1e-4, **training},
    }
    run_train(config, zero_stage=0, output_dir=output)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--output", default=str(REPO / "results" / "train"))
    ap.add_argument("--only", default=None, metavar="SUFFIX",
                    help="run a single config in THIS process (the "
                         "per-config worker mode)")
    ap.add_argument("--missing", action="store_true",
                    help="matrix mode, but only configs with neither a "
                         "measured nor a boundary artifact — resume a "
                         "matrix interrupted by a tunnel outage without "
                         "re-measuring the landed rungs")
    args = ap.parse_args()

    if args.only:
        _run_one(args.only, args.iters, args.output)
        return 0

    from _publish_common import run_worker_matrix

    suffixes = [s for s, _, _, _ in CONFIGS]
    if args.missing:
        out = Path(args.output)
        suffixes = [
            s for s in suffixes
            if not (out / f"{_artifact_name(s)}.json").exists()
            and not (out / f"{_artifact_name(s)}_infeasible.json").exists()
        ]
        print(f"--missing: {len(suffixes)} config(s) to run: {suffixes}",
              flush=True)

    return run_worker_matrix(
        __file__,
        suffixes,
        only_str=lambda s: s,
        artifact_name=_artifact_name,
        expected_fail_ok=EXPECTED_FAIL_OK,
        write_boundary=write_boundary_artifact,
        output=args.output,
        iters=args.iters,
    )


if __name__ == "__main__":
    sys.exit(main())
