#!/usr/bin/env python
"""Publish the real-TPU-chip TRAIN artifact set under ``results/train/``.

The train-side analogue of ``publish_tpu_e2e.py`` — and the provenance
record for every ``*_chip_*`` train artifact: every committed
``results/train/train_ddp_1B_train_chip_*.json`` has a matching suffix in
``CONFIGS`` (round 3's ad-hoc ``sgd`` artifact was superseded by the
``sgd_remat_full`` config, which measures the identical configuration
with provenance).  Covers the two round-4 asks:

- **the reference's optimizer on the chip**: the reference trains only
  with Adam (``/root/reference/test/ccl.py:74-117``,
  ``test/ds_mpi_test.py:16-24``).  Both the VERBATIM fp32-moments Adam
  (fits since the chained-timing carry-donation fix halved resident
  TrainState HBM, ``utils/timing.py``) and the memory-reduced
  ``training.moments_dtype: bfloat16`` variant (numerics vs fp32 Adam
  asserted in ``tests/test_optim.py``) are measured.
- **the remat-policy ladder**: remat off / "dots" (save matmul outputs) /
  "full" (save nothing) at the same 1B/b8/s512 shape, isolating the
  memory/recompute trade the round-3 117 TFLOP/s number silently included
  (every layer full-remat).  Artifacts record MODEL-flops MFU and the
  device-work ``*_incl_recompute`` rate.

Usage: python scripts/publish_tpu_train.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # _publish_common

# (name_suffix, training overrides, model overrides)
CONFIGS: tuple[tuple[str, dict, dict], ...] = (
    # reference-parity optimizer, memory-reduced variant
    ("adam_bf16m",
     {"optimizer": "adam", "moments_dtype": "bfloat16"},
     {"remat": True, "remat_policy": "full"}),
    # the reference's optimizer VERBATIM (fp32 moments) — fits since the
    # chained-timing carry-donation fix
    ("adam_fp32m",
     {"optimizer": "adam"},
     {"remat": True, "remat_policy": "full"}),
    # remat-policy ladder at fixed optimizer (stateless SGD isolates the
    # activation-memory axis from optimizer-state memory)
    ("sgd_remat_off", {"optimizer": "sgd"}, {"remat": False}),
    ("sgd_remat_dots", {"optimizer": "sgd"},
     {"remat": True, "remat_policy": "dots"}),
    ("sgd_remat_full", {"optimizer": "sgd"},
     {"remat": True, "remat_policy": "full"}),
    # best-policy headline at the reference optimizer config
    ("adam_bf16m_dots",
     {"optimizer": "adam", "moments_dtype": "bfloat16"},
     {"remat": True, "remat_policy": "dots"}),
    # the TPU-idiomatic large-model optimizer (factored second moments)
    ("adafactor", {"optimizer": "adafactor"},
     {"remat": True, "remat_policy": "full"}),
)

# sgd_remat_off: the no-remat rung of the ladder — measured OOM at compile
# (19.30G program HBM vs 15.75G usable: 24 layers x [B,S,ffn] bf16
# activations stored for backward); its failure IS the ladder's data point
# for "remat off", quantifying what remat buys.
#
# adam_fp32m is NOT here: it OOMed only while the chained timing loop kept
# two TrainState copies resident; with the carry-donation fix
# (utils/timing.py::time_fn_chained) the reference's verbatim optimizer
# measures cleanly (results/train/train_ddp_1B_train_chip_adam_fp32m.json),
# so a failure there is a real regression again.
EXPECTED_FAIL_OK = {"sgd_remat_off"}

BATCH_SIZE = 8
SEQ_LEN = 512


def _experiment_name(suffix: str) -> str:
    return f"1B_train_chip_{suffix}"


def _artifact_name(suffix: str) -> str:
    """Must match ``run_train``'s ``train_<mode>_<name>.json`` (zero stage 0
    = mode "ddp", ``dlbb_tpu/train/loop.py``)."""
    return f"train_ddp_{_experiment_name(suffix)}"


def _boundary_reason(suffix: str) -> str:
    from dlbb_tpu.models.configs import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["1B"]
    assert suffix == "sgd_remat_off", suffix
    # stored-for-backward activation footprint is dominated by the per-layer
    # [B, S, ffn] intermediates (bf16)
    act_gib = (cfg.num_layers * BATCH_SIZE * SEQ_LEN
               * cfg.ffn_intermediate * 2 / 2**30)
    return (
        f"without remat every layer's forward activations stay resident "
        f"for the backward pass ({act_gib:.1f} GiB PER stacked "
        f"[L,B,S,ffn] bf16 intermediate at L={cfg.num_layers}, "
        f"B={BATCH_SIZE}, S={SEQ_LEN}, ffn={cfg.ffn_intermediate}, and "
        f"XLA keeps several plus the fp32 hidden streams: 19.30G program "
        f"HBM vs 15.75G usable at compile) — the measured remat ladder "
        f"points are the dots/full artifacts"
    )


def write_boundary_artifact(suffix: str, output: str, exit_code: int,
                            observed_error: str) -> Path:
    boundary = {
        "experiment": {"name": _experiment_name(suffix)},
        "status": "infeasible",
        "reason": _boundary_reason(suffix),
        "observed_error": observed_error,
        "exit_code": exit_code,
    }
    out = Path(output)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{_artifact_name(suffix)}_infeasible.json"
    path.write_text(json.dumps(boundary, indent=2) + "\n")
    return path


def _run_one(suffix: str, iters: int, output: str) -> None:
    # validate the suffix BEFORE any JAX/runtime init: a typo must fail in
    # milliseconds, not after grabbing the chip
    match = [(t, m) for s, t, m in CONFIGS if s == suffix]
    if not match:
        raise SystemExit(
            f"unknown config {suffix!r}; known: "
            f"{[s for s, _, _ in CONFIGS]}"
        )
    training, model_over = match[0]

    import jax

    print(f"devices: {jax.devices()}", flush=True)

    from dlbb_tpu.train.loop import run_train
    config = {
        "experiment": {"name": _experiment_name(suffix)},
        "model": {"size": "1B", "attention": "full", **model_over},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": BATCH_SIZE, "sequence_length": SEQ_LEN,
                  "seed": 42},
        "execution": {"warmup_iterations": 2,
                      "benchmark_iterations": iters},
        "training": {"learning_rate": 1e-4, **training},
    }
    run_train(config, zero_stage=0, output_dir=output)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--output", default=str(REPO / "results" / "train"))
    ap.add_argument("--only", default=None, metavar="SUFFIX",
                    help="run a single config in THIS process (the "
                         "per-config worker mode)")
    args = ap.parse_args()

    if args.only:
        _run_one(args.only, args.iters, args.output)
        return 0

    from _publish_common import run_worker_matrix

    return run_worker_matrix(
        __file__,
        [s for s, _, _ in CONFIGS],
        only_str=lambda s: s,
        artifact_name=_artifact_name,
        expected_fail_ok=EXPECTED_FAIL_OK,
        write_boundary=write_boundary_artifact,
        output=args.output,
        iters=args.iters,
    )


if __name__ == "__main__":
    sys.exit(main())
