#!/usr/bin/env python
"""Publish the in-repo baseline artifact corpus.

The reference's §6 baseline IS its checked-in artifacts (~1,700 result/stats
files under ``collectives/1d/results+stats`` and ``collectives/3d/...``).
This driver produces the dlbb_tpu analogue and is the provenance record for
everything under ``results/`` and ``stats/``:

- ``results/1d/xla_tpu/``        canonical reference grid (8 ops x
  {1KB,64KB,1MB,16MB} x ranks {2,4,8}; 16/32 via the 1d16/1d32 stages) plus the extended
  {64MB,256MB,1GB} sizes of the north-star curve (BASELINE.json metric)
- ``results/3d/xla_tpu/``        reference 3D grid (5 ops x B x S x H x
  ranks {4,8}, ``collectives/3d/openmpi.py:19-31``)
- ``results/variants/<impl>/``   allreduce tuning matrix over the executable
  variants (mesh topology / axis order / hierarchical / fusion-off) — the
  analogue of the reference's ``dsccl_{ring,rabs,...}`` result dirs
  (``collectives/3d/launch_dsccl.sh:34-65``)
- ``results/train/``             ZeRO-ladder train benchmarks incl. the
  fusion on/off (combiner-passes) comparison
- ``stats/...``                  the stats pipelines run over all of the
  above (reference ``collectives/{1d,3d}/stats.py`` schema)

Everything runs on the CPU-simulated 8-device mesh (this image has one TPU
chip; collectives are degenerate on one device — SURVEY §4's
"multi-node without a cluster" model).  The host has ONE core, so the sweeps
are time-budgeted: per-config measurement is capped (``max_config_seconds``)
and iteration counts recorded in each artifact are the actual ones.  Configs
whose global footprint would not fit host RAM are skipped
(``max_global_bytes``), mirroring the reference's per-config error-skip.

Usage: python scripts/publish_baselines.py [--stage 1d|3d|variants|train|stats|baseline|all]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path


REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

# The simulated device count is a process-start property (XLA_FLAGS).  The
# default 8-device mesh covers the reference's {2,4,8} rank sweeps; the
# reference's HEADLINE rows are at 16 ranks (BASELINE.md: oneCCL allreduce
# "16MB" @ 16 ranks) and its rank axis extends through 32/56, so the
# ``1d16``/``3d16``/``1d32`` stages run in SEPARATE invocations with
# DLBB_PUBLISH_DEVICES=16 (or 32).
N_DEVICES = int(os.environ.get("DLBB_PUBLISH_DEVICES", "8"))
force_cpu_simulation(N_DEVICES)

from dlbb_tpu.bench.runner import (  # noqa: E402
    DATA_SIZES_1D,
    EXTENDED_DATA_SIZES_1D,
    Sweep1D,
    Sweep3D,
)
from dlbb_tpu.bench.runner import run_sweep as _run_sweep  # noqa: E402
from dlbb_tpu.bench.schedule import MANIFEST_NAME  # noqa: E402

RESULTS = REPO / "results"
STATS = REPO / "stats"


def run_sweep(sweep, **kw):
    """The library driver plus a per-stage log of the sweep engine's
    manifest (wall vs compile seconds, persistent-cache hits) — the
    publisher is the time-budgeted caller the compile-ahead pipeline and
    warm-cache re-runs exist for, so every stage records its win."""
    t0 = time.time()
    written = _run_sweep(sweep, **kw)
    manifest = Path(sweep.output_dir) / MANIFEST_NAME
    if manifest.exists():
        m = json.loads(manifest.read_text())
        if m.get("timestamp", 0) < t0:
            # a fully-gated run (e.g. a 16-rank stage without the
            # DLBB_PUBLISH_DEVICES=16 invocation) writes no manifest —
            # never report a previous run's numbers as this run's
            return written
        cc = m.get("compile_cache", {})
        log(
            f"  [engine] wall {m.get('wall_seconds', 0):.1f}s, compile "
            f"{m.get('compile_seconds_total', 0):.1f}s "
            f"({'pipelined' if m.get('pipeline') else 'serial'}; "
            f"xla-cache hits {cc.get('persistent_hits', 0)}/"
            f"{cc.get('persistent_hits', 0) + cc.get('persistent_misses', 0)})"
        )
    return written

# Sweeps resume by default: the publisher is time-budgeted and routinely
# interrupted, and one-JSON-per-config makes resumption natural (the
# reference resumes the same way, SURVEY §5.4).  ``--fresh`` re-measures
# everything — REQUIRED after changing measurement/timing code, otherwise a
# rerun would silently rebuild stats from the stale committed corpus.
RESUME = True

GIB = 2**30

# Executable variant matrix (the fusion/threshold XLA_FLAGS variants need a
# real pod launcher and are excluded — see dlbb_tpu/comm/variants.py).
# "nofuse" is also excluded here: disabling the collective-combiner passes
# is a null experiment on single-collective 1D programs (nothing to
# combine — variants.py admits this); its honest measurement is the train
# stage's fused/nofuse comparison over many-collective ZeRO steps.
EXECUTABLE_VARIANTS = (
    "default",
    "ring",
    "grid2x4",
    "grid4x2",
    "hier2x4",
    "hier4x2",
    "grid2x2x2",
    "hier2x2x2",
)

TRAIN_MODEL = {
    "hidden_size": 256,
    "num_layers": 4,
    "num_heads": 8,
    "ffn_intermediate": 1024,
    "attention": "full",
    "dtype": "float32",
}

NOFUSE_OPTIONS = {
    "xla_disable_hlo_passes":
        "all-reduce-combiner,all-gather-combiner,reduce-scatter-combiner",
}


def log(msg: str) -> None:
    print(f"[publish {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def stage_1d() -> None:
    log("1D canonical grid (+ extended sizes)")
    out = RESULTS / "1d" / "xla_tpu"
    ext_sizes = tuple(
        (k, v) for k, v in EXTENDED_DATA_SIZES_1D.items()
        if k not in DATA_SIZES_1D
    )
    run_sweep(Sweep1D(
        output_dir=str(out),
        max_config_seconds=20.0,
        max_global_bytes=24 * GIB,
        resume=RESUME,
    ))
    # extended sizes: fewer rank counts, tighter budget — the big-payload
    # tail of the north-star 1KB..1GB curve
    run_sweep(Sweep1D(
        data_sizes=ext_sizes,
        rank_counts=(4, 8),
        output_dir=str(out),
        max_config_seconds=15.0,
        # quadratic-footprint ops (allgather/gather/alltoall) at the big
        # labels would otherwise spend tens of minutes shuffling host RAM
        # on the single simulating core — informative about nothing; the
        # skip is logged and the absence is the honest artifact
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


# The north-star trio (BASELINE.json configs[1]: "all-reduce / all-gather /
# broadcast, 1 KB-1 GB, fp32+bf16").  The bf16 half is the canonical grid;
# these stages publish the fp32 half into the SAME directory with
# dtype-suffixed filenames (runner._result_filename).
FP32_OPS = ("allreduce", "allgather", "broadcast")


def stage_1dfp32() -> None:
    log("1D fp32 north-star curve (allreduce/allgather/broadcast, 1KB-1GB)")
    run_sweep(Sweep1D(
        operations=FP32_OPS,
        data_sizes=tuple(EXTENDED_DATA_SIZES_1D.items()),
        dtype="float32",
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=15.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_1dfp32_16() -> None:
    """fp32 curve at the reference's headline rank count (16) — separate
    DLBB_PUBLISH_DEVICES=16 invocation, like stage_1d16."""
    if not _require_devices(16, "1dfp32_16"):
        return
    log("1D fp32 north-star curve @ 16 ranks")
    run_sweep(Sweep1D(
        operations=FP32_OPS,
        data_sizes=tuple(EXTENDED_DATA_SIZES_1D.items()),
        rank_counts=(16,),
        dtype="float32",
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=10.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


# The big-payload tail of the north-star curve: bandwidth measurements get
# interesting exactly where the default publisher budget thins out
# (VERDICT r3 weak #5).  This stage extends the ranks axis of the
# 256MB/1GB labels (both dtypes).  The 8 GiB global-footprint cap is
# EMPIRICAL, not cautious: a 10 GiB allgather config was measured at
# > 20 minutes without completing one budgeted sample on the single
# simulating core (in-process rendezvous thrash — the same wall the 3D
# stage documents); the honest artifact above the cap is the logged skip.
TAIL_SIZES = tuple(
    (k, v) for k, v in EXTENDED_DATA_SIZES_1D.items()
    if k in ("256MB", "1GB")
)


def stage_1dfp16() -> None:
    """fp16 parity slice: the reference's 1D corpus is measured on fp16
    payloads (``collectives/1d/openmpi.py:247-248``).  Byte counts per
    config already matched (bf16 and fp16 are both 2 B/element over the
    same element counts); what this slice adds is DTYPE identity — the
    same float16 numeric type the reference timed — making these the
    closest apples-to-apples rows of the comparison join.  All 8 ops,
    canonical sizes, ranks {2,4,8} (16 via the 1dfp16_16 stage)."""
    log("1D fp16 parity slice (all 8 ops, canonical sizes)")
    run_sweep(Sweep1D(
        dtype="float16",
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=15.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_1dfp16_16() -> None:
    if not _require_devices(16, "1dfp16_16"):
        return
    log("1D fp16 parity slice @ 16 ranks")
    run_sweep(Sweep1D(
        rank_counts=(16,),
        dtype="float16",
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=10.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_1dtail() -> None:
    log("1D big-payload tail (256MB/1GB, bf16+fp32, ranks 2/4/8)")
    for dtype in ("bfloat16", "float32"):
        run_sweep(Sweep1D(
            operations=FP32_OPS,
            data_sizes=TAIL_SIZES,
            rank_counts=(2, 4, 8),
            dtype=dtype,
            output_dir=str(RESULTS / "1d" / "xla_tpu"),
            max_config_seconds=20.0,
            max_global_bytes=8 * GIB,
            resume=RESUME,
        ))


def stage_1dtail_16() -> None:
    """The 16-rank rung of the big-payload tail (DLBB_PUBLISH_DEVICES=16
    invocation)."""
    if not _require_devices(16, "1dtail_16"):
        return
    log("1D big-payload tail @ 16 ranks")
    for dtype in ("bfloat16", "float32"):
        run_sweep(Sweep1D(
            operations=FP32_OPS,
            data_sizes=TAIL_SIZES,
            rank_counts=(16,),
            dtype=dtype,
            output_dir=str(RESULTS / "1d" / "xla_tpu"),
            max_config_seconds=15.0,
            max_global_bytes=8 * GIB,
            resume=RESUME,
        ))


def stage_3d() -> None:
    log("3D reference grid")
    run_sweep(Sweep3D(
        output_dir=str(RESULTS / "3d" / "xla_tpu"),
        max_config_seconds=8.0,
        # 4 GiB global-footprint cap: above it a single iteration on the
        # one simulating core takes minutes (rendezvous threads thrashing
        # host RAM) and the full reference grid would not finish in a day.
        # Skips are logged per config; the honest artifact for those rows
        # is their absence + the skip line, not a number measuring nothing
        # but swap behaviour.
        max_global_bytes=4 * GIB,
        resume=RESUME,
    ))


def _require_devices(n: int, stage: str) -> bool:
    if N_DEVICES < n:
        log(f"SKIP stage {stage}: needs DLBB_PUBLISH_DEVICES={n} "
            f"(have {N_DEVICES}) — rerun as "
            f"DLBB_PUBLISH_DEVICES={n} python scripts/publish_baselines.py "
            f"--stage {stage}")
        return False
    return True


def stage_1d16() -> None:
    """16-rank canonical 1D grid — the reference's HEADLINE rank count
    (BASELINE.md: every 1D headline row, e.g. oneCCL allreduce "16MB"
    4.94 ms / 23.29 GB/s, is at 16 ranks;
    ``collectives/1d/stats/dsccl/benchmark_statistics.csv:18``).  Runs in a
    separate 16-device invocation (DLBB_PUBLISH_DEVICES=16)."""
    if not _require_devices(16, "1d16"):
        return
    log("1D canonical grid @ 16 ranks (reference headline rank count)")
    run_sweep(Sweep1D(
        rank_counts=(16,),
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=15.0,
        max_global_bytes=24 * GIB,
        resume=RESUME,
    ))


def stage_1d32() -> None:
    """32-rank canonical 1D grid — the reference's 1D rank axis extends
    through 32 and 56 ranks (``collectives/1d/openmpi.py:20``); 32 is the
    largest power-of-two rung this host can simulate in reasonable time.
    Runs in a DLBB_PUBLISH_DEVICES=32 invocation."""
    if not _require_devices(32, "1d32"):
        return
    log("1D canonical grid @ 32 ranks")
    run_sweep(Sweep1D(
        rank_counts=(32,),
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=10.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_1d56() -> None:
    """56-rank canonical 1D grid — the LAST rung of the reference's rank
    axis (its 56-core node's full width, ``collectives/1d/openmpi.py:20``).
    With this stage the corpus covers every reference 1D rank count
    {2,4,8,16,32,56}.  Runs in a DLBB_PUBLISH_DEVICES=56 invocation."""
    if not _require_devices(56, "1d56"):
        return
    log("1D canonical grid @ 56 ranks (full reference rank axis)")
    run_sweep(Sweep1D(
        rank_counts=(56,),
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=10.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_3d16() -> None:
    """16-rank 3D grid, all 5 ops — the reference sweeps 3D at ranks
    {4,8,16} (``collectives/3d/openmpi.py:19``); with this stage the 3D
    corpus covers the full reference rank axis."""
    if not _require_devices(16, "3d16"):
        return
    log("3D grid @ 16 ranks (all 5 ops)")
    run_sweep(Sweep3D(
        rank_counts=(16,),
        output_dir=str(RESULTS / "3d" / "xla_tpu"),
        max_config_seconds=8.0,
        max_global_bytes=4 * GIB,
        resume=RESUME,
    ))


def stage_variants() -> None:
    log("allreduce variant matrix")
    for name in EXECUTABLE_VARIANTS:
        log(f"  variant {name}")
        run_sweep(Sweep1D(
            variant=name,
            operations=("allreduce",),
            output_dir=str(RESULTS / "variants" / _impl(name)),
            max_config_seconds=20.0,
            max_global_bytes=24 * GIB,
            resume=RESUME,
        ))


# 16-rank variant rung (VERDICT r3 weak #4: the winner report compared at
# exactly one rank count): flat variants at 16 ranks + the 16-device
# grid/hier mesh shapes.  Separate DLBB_PUBLISH_DEVICES=16 invocation.
VARIANTS_16 = ("default", "ring", "grid2x8", "grid4x4", "hier2x8",
               "hier4x4")


def stage_variants16() -> None:
    if not _require_devices(16, "variants16"):
        return
    log("allreduce variant matrix @ 16 ranks")
    for name in VARIANTS_16:
        log(f"  variant {name}")
        run_sweep(Sweep1D(
            variant=name,
            operations=("allreduce",),
            rank_counts=(16,),
            output_dir=str(RESULTS / "variants" / _impl(name)),
            max_config_seconds=15.0,
            max_global_bytes=24 * GIB,
            resume=RESUME,
        ))


# 3D-shape allreduce for the two winning 1D variants (ring swept the
# size axis at 8 ranks, grid4x2 took 1KB — stats/variants) — the
# reference tuned its CCL algorithms on the 3D LLM-shaped sweep
# (``collectives/3d/launch_dsccl.sh``), so the winners get 3D numbers too.
VARIANTS_3D = ("ring", "grid4x2")


def stage_variants3d() -> None:
    log("3D allreduce for the winning variants")
    for name in VARIANTS_3D:
        log(f"  variant {name} (3D)")
        run_sweep(Sweep3D(
            variant=name,
            operations=("allreduce",),
            output_dir=str(RESULTS / "variants3d" / _impl(name)),
            max_config_seconds=8.0,
            max_global_bytes=4 * GIB,
            resume=RESUME,
        ))


# The reference's CCL tuning ran on a REDUCED 3D grid — allreduce only,
# B {8,16} x S {2048,4096} x H {2048,4096}, ranks {4,8(,16)}
# (``collectives/3d/dsccl.py:20-28``) — and concentrated its algorithm /
# worker / fusion matrix there (19 result dirs, SURVEY §2.3).  This stage
# gives EVERY executable variant rows on that grid (the full-grid
# ``variants3d`` stage covers only the two 1D winners); rank-gated mesh
# shapes (grid/hier need exactly 8 ranks) and memory-capped cells are
# logged skips, like the reference's OOM holes.
TUNING_GRID_3D = {
    "batch_sizes": (8, 16),
    "seq_lengths": (2048, 4096),
    "hidden_dims": (2048, 4096),
}


# Rank counts the FULL-grid variants3d stage sweeps (Sweep3D default).
# The tuning grid is a subgrid of the full grid at these rank counts, so
# for VARIANTS_3D members the tuning stage would re-run shared cells into
# the same output dirs under a different memory cap (8 GiB vs 4 GiB) —
# making the surviving artifact order-dependent under --fresh.  The dedup
# below drops exactly those (variant, rank) combinations; rank counts the
# full-grid stage does NOT cover (e.g. ring @ 16) are kept.
FULL_GRID_RANKS = (4, 8)


def _tuning_grid_members(variants, rank_counts):
    """Deterministic (variant, rank_counts) pairs for a tuning-grid run:
    input order preserved, "default" skipped (the 3d/3d16 stages cover
    it), and VARIANTS_3D members deduplicated against the full-grid
    stage's rank counts.  Pure so tests can pin the dedup."""
    members = []
    for name in variants:
        if name == "default":
            continue
        if name in VARIANTS_3D:
            ranks = tuple(r for r in rank_counts
                          if r not in FULL_GRID_RANKS)
        else:
            ranks = tuple(rank_counts)
        if ranks:
            members.append((name, ranks))
    return tuple(members)


def _run_tuning_grid(variants, rank_counts, label: str) -> None:
    """One reduced-tuning-grid sweep per variant (dedup rules in
    ``_tuning_grid_members``)."""
    for name, ranks in _tuning_grid_members(variants, rank_counts):
        log(f"  variant {name} ({label})")
        run_sweep(Sweep3D(
            variant=name,
            operations=("allreduce",),
            batch_sizes=TUNING_GRID_3D["batch_sizes"],
            seq_lengths=TUNING_GRID_3D["seq_lengths"],
            hidden_dims=TUNING_GRID_3D["hidden_dims"],
            rank_counts=ranks,
            output_dir=str(RESULTS / "variants3d" / _impl(name)),
            max_config_seconds=8.0,
            max_global_bytes=8 * GIB,
            resume=RESUME,
        ))


def stage_variants3d_tuning() -> None:
    log("3D allreduce tuning grid: ALL executable variants "
        "(reference dsccl.py reduced grid)")
    _run_tuning_grid(EXECUTABLE_VARIANTS, (4, 8), "3D tuning grid")


def stage_variants3d_tuning16() -> None:
    """The 16-rank rung of the reference's tuning grid (its
    ``RANK_COUNTS = [4, 8, 16]``, ``collectives/3d/dsccl.py:20``):
    the 16-rank-shaped variants + flat ring on the same reduced grid.
    Runs in a DLBB_PUBLISH_DEVICES=16 invocation."""
    if not _require_devices(16, "variants3d_tuning16"):
        return
    log("3D allreduce tuning grid @ 16 ranks")
    _run_tuning_grid(VARIANTS_16, (16,), "3D tuning grid, 16 ranks")


def _impl(variant: str) -> str:
    return "xla_tpu" if variant == "default" else f"xla_tpu_{variant}"


def stage_train() -> None:
    from dlbb_tpu.train.loop import run_train

    out = RESULTS / "train"
    for stage in (0, 1, 2, 3):
        for fusion in (True, False) if stage in (0, 3) else ((True,)):
            execution = {"warmup_iterations": 2, "benchmark_iterations": 10}
            suffix = "fused"
            if not fusion:
                execution["compiler_options"] = dict(NOFUSE_OPTIONS)
                suffix = "nofuse"
            name = f"zero{stage}_dp8_{suffix}"
            log(f"  train {name}")
            config = {
                "experiment": {"name": name},
                "model": dict(TRAIN_MODEL),
                "parallelism": {"world_size": 1, "data_parallel": 8},
                "input": {"batch_size": 16, "sequence_length": 64,
                          "seed": 42},
                "execution": execution,
                "training": {"learning_rate": 1e-3},
            }
            run_train(config, zero_stage=stage, output_dir=str(out))


# Parallelism-family benchmark matrix (VERDICT r3 missing #4): families
# live in the library (single source of truth shared with the `reports`
# CLI).  Model is the small train-stage geometry so the simulated mesh
# measures schedules, not host-core matmul throughput.  Sequence length
# 128 gives the sp families a real sequence to split.
from dlbb_tpu.stats.parallelism_report import (  # noqa: E402
    DEFAULT_FAMILIES as PARALLELISM_FAMILIES,
)

_PARALLELISM_CONFIGS: dict[str, tuple[dict, dict, dict]] = {
    # name: (model overrides, parallelism block, training overrides)
    "pp2_gpipe": ({}, {"world_size": 2, "data_parallel": 2,
                       "pipeline_parallel": 2, "num_microbatches": 4}, {}),
    "pp2_1f1b": ({}, {"world_size": 2, "data_parallel": 2,
                      "pipeline_parallel": 2, "num_microbatches": 4},
                 {"pipeline_schedule": "1f1b"}),
    "sp2_ring": ({"attention": "ring"},
                 {"world_size": 2, "data_parallel": 2,
                  "sequence_parallel": 2}, {}),
    "sp2_ulysses": ({"attention": "ulysses"},
                    {"world_size": 2, "data_parallel": 2,
                     "sequence_parallel": 2}, {}),
    "ep2_moe_dense": ({"num_experts": 4, "moe_dispatch": "dense"},
                      {"world_size": 2, "data_parallel": 2,
                       "expert_parallel": 2},
                      {"moe_aux_loss_weight": 0.01}),
    "ep2_moe_capacity": ({"num_experts": 4, "moe_dispatch": "capacity"},
                         {"world_size": 2, "data_parallel": 2,
                          "expert_parallel": 2},
                         {"moe_aux_loss_weight": 0.01}),
    "ga2_divisible_b16": ({}, {"world_size": 2, "data_parallel": 4},
                          {"gradient_accumulation": 2}),
    "ga2_reshard_b20": ({}, {"world_size": 2, "data_parallel": 4},
                        {"gradient_accumulation": 2}),
}

# per-config input batch overrides (default 16)
_PARALLELISM_BATCH = {"ga2_reshard_b20": 20}


def stage_parallelism() -> None:
    from dlbb_tpu.train.loop import run_train

    out = RESULTS / "parallelism"
    log("parallelism-family benchmarks (step-time pairs)")
    for name, (model_over, par, train_over) in _PARALLELISM_CONFIGS.items():
        log(f"  {name}")
        config = {
            "experiment": {"name": name},
            "model": dict(TRAIN_MODEL, **model_over),
            "parallelism": par,
            "input": {"batch_size": _PARALLELISM_BATCH.get(name, 16),
                      "sequence_length": 128, "seed": 42},
            "execution": {"warmup_iterations": 2,
                          "benchmark_iterations": 10},
            "training": {"learning_rate": 1e-3, **train_over},
        }
        run_train(config, zero_stage=0, output_dir=str(out))
    from dlbb_tpu.stats.parallelism_report import write_parallelism_report

    rows = write_parallelism_report(out, STATS / "parallelism",
                                    PARALLELISM_FAMILIES)
    for r in rows:
        if r["winner"]:
            log(f"  winner {r['family']}: {r['member']} "
                f"({r['step_time_mean_s']} s)")


# Long-context CP scaling (VERDICT r4 #6): ring vs Ulysses across the
# sequence axis the reference only ever touched as payload bytes
# (SURVEY §5.7 — its "long context" is collective payload size; it has no
# context parallelism).  B=1, small model, S {8192,16384,32768},
# sp {2,4,8} on the simulated mesh.  Dense-score footprint is the binding
# constraint on this host: Ulysses computes full-S attention per local
# head ([B, H/P, S, S] x P devices = B*H*S^2 global), ring only a
# [S/P, S/P] block per device (B*H*S^2/P global) — configs whose
# estimated resident bytes exceed the cap are skipped with a committed
# boundary artifact, like the chip ladder's OOM rungs.
# deliberately tiny (1 layer, h=64): on this single-core host the sim
# mesh sustains only ~2 GFLOP/s, and the S^2 attention term dominates —
# a 2-layer h=128 model measured 86 s/step at S=8192/sp2, pricing the
# S=32768 rows out entirely.  Both impls share the model, so the
# ring-vs-Ulysses ordering (the signal) is preserved; 8 heads keeps
# every sp degree Ulysses-divisible.
CP_SCALING_MODEL = {
    "hidden_size": 64,
    "num_layers": 1,
    "num_heads": 8,
    "ffn_intermediate": 256,
    "dtype": "float32",
}
CP_SEQ_LENGTHS = (8192, 16384, 32768)
CP_SP_DEGREES = (2, 4, 8)
# fwd scores + backward recompute/grad residency, measured-informed fudge
CP_RESIDENCY_FACTOR = 3
CP_FOOTPRINT_CAP = 48 * GIB  # of the 125 GiB host pool
# Ring's total attention compute is Theta(S^2 * h) regardless of sp (P
# blocks of (S/P)^2, and the 1-core host simulates every device
# serially), so EVERY S=32768 ring cell costs the same ~40 min here
# (measured anchor: 286 s/step at S=16384/sp2, x4 for S^2).  The time
# budget admits one long-S cell: sp=8 carries the S axis; the other sp
# degrees are covered at S<=16384 and land as logged time-cap skips.
CP_LONG_S_SP: dict[int, tuple[int, ...]] = {32768: (8,)}
# single measured iteration at the longest S (a second ~20-min sample
# buys no ordering information on a sim mesh)
CP_BENCH_ITERS = {32768: 1}
# Cells that kill the PROCESS rather than raise: XLA:CPU's in-process
# collective rendezvous has a hard 40 s termination timeout (fatal
# CHECK, not catchable — "Exiting to ensure a consistent program
# state"), and at S=32768 the single core cannot bring 8 device
# threads to the ring's collective-permute rendezvous in time
# (observed 2026-07-31: 6/8 arrived).  The stage writes the boundary
# artifact itself instead of re-executing the crash — this keeps
# --fresh runs alive through the rest of the publisher (the train
# publisher isolates this failure class with worker subprocesses;
# one known cell doesn't warrant that machinery here).
CP_KNOWN_INFEASIBLE = {("ring", 32768, 8)}


def _cp_time_skip_reason(seq: int, allowed_sp) -> str:
    """The ``skipped_estimated_time`` artifact reason.  Pure (tested in
    test_publish_scripts): the wording must not claim the budget-admitted
    sp cell produced a measurement — at S=32768 that cell is itself the
    CP_KNOWN_INFEASIBLE rendezvous-timeout cell, so the measured S axis
    ends at 16384 and S=32768 is boundary-documented only (matching
    CP_SCALING.md and the infeasible artifact's own wording)."""
    return (
        f"ring-family attention compute is Theta(S^2) independent of sp "
        f"on a serially-simulated mesh; at S={seq} each cell costs "
        f"~40 min on this single-core host (measured anchor 286 s/step "
        f"at S=16384/sp2).  The time budget admits only sp "
        f"{list(allowed_sp)} here, and that cell is itself the XLA:CPU "
        f"rendezvous-timeout infeasible cell (see its boundary artifact) "
        f"— so the measured S axis ends at 16384 and S={seq} is "
        f"boundary-documented only."
    )


def _cp_score_bytes(impl: str, seq: int, sp: int) -> int:
    """Global resident bytes of the attention score tensors (fp32)."""
    b, h = 1, CP_SCALING_MODEL["num_heads"]
    per = b * h * seq * seq * 4
    if impl == "ring":
        per //= sp  # one [S/P, S/P] block per device at a time
    return per * CP_RESIDENCY_FACTOR


def stage_cp_scaling() -> None:
    from dlbb_tpu.train.loop import run_train
    from dlbb_tpu.utils.config import save_json

    out = RESULTS / "parallelism" / "cp_scaling"
    out.mkdir(parents=True, exist_ok=True)
    log("long-context CP scaling: ring vs Ulysses, S x sp grid")
    for seq in CP_SEQ_LENGTHS:
        for sp in CP_SP_DEGREES:
            for impl in ("ring", "ulysses"):
                name = f"cp_s{seq}_sp{sp}_{impl}"
                path = out / f"train_ddp_{name}.json"
                if RESUME and path.exists():
                    log(f"  [resume-skip] {name}")
                    continue
                if (impl, seq, sp) in CP_KNOWN_INFEASIBLE:
                    log(f"  [skip-infeasible] {name}: XLA:CPU rendezvous "
                        "termination timeout (fatal CHECK; boundary "
                        "artifact written, cell not re-executed)")
                    save_json({
                        "experiment": {"name": name},
                        "status": "infeasible",
                        "reason": (
                            "XLA:CPU's in-process collective rendezvous "
                            "enforces a hard 40 s termination timeout "
                            "(rendezvous.cc, no tunable flag in this "
                            f"jaxlib): at S={seq} each simulated device "
                            f"computes [{seq // sp},{seq // sp}] ring "
                            "attention blocks between collective-permute "
                            "steps, and the single-core host cannot bring "
                            f"all {sp} device threads to the rendezvous "
                            "in time (observed: 'Expected 8 threads to "
                            "join the rendezvous, but only 6 of them "
                            "arrived on time', fatal check after 40 s).  "
                            "Same runtime boundary class as the "
                            "full-depth 13B training abort documented in "
                            "docs/13b_single_chip.md.  The S axis is "
                            "measured to 16384 (all sp degrees); on real "
                            "TPU hardware the per-device block compute "
                            "runs on the chip and no host-thread "
                            "rendezvous exists."
                        ),
                        "observed_error": (
                            "F0731 07:28:13 rendezvous.cc:127 Termination "
                            "timeout for `collective permute "
                            "RendezvousKey{...global_devices=[0..7]...}` "
                            "of 40 seconds exceeded. Exiting to ensure a "
                            "consistent program state. Expected 8 threads "
                            "to join the rendezvous, but only 6 of them "
                            "arrived on time."
                        ),
                    }, str(path))
                    continue
                # footprint cap FIRST: a cell that cannot fit in RAM at
                # any sp must say so — blaming the time budget would
                # misattribute the skip (Ulysses at S=32768 is
                # footprint-bound at EVERY sp)
                est = _cp_score_bytes(impl, seq, sp)
                allowed_sp = CP_LONG_S_SP.get(seq, CP_SP_DEGREES)
                if est <= CP_FOOTPRINT_CAP and sp not in allowed_sp:
                    log(f"  [skip-time] {name}: S={seq} cells cost "
                        "~40 min each on this single-core host "
                        "(S^2 anchor), budget admits sp "
                        f"{allowed_sp} only")
                    save_json({
                        "experiment": {"name": name},
                        "status": "skipped_estimated_time",
                        "reason": _cp_time_skip_reason(seq, allowed_sp),
                    }, str(path))
                    continue
                if est > CP_FOOTPRINT_CAP:
                    log(f"  [skip-mem] {name}: est. {est / GIB:.0f} GiB "
                        f"score residency > cap {CP_FOOTPRINT_CAP / GIB:.0f}"
                        " GiB")
                    save_json({
                        "experiment": {"name": name},
                        "status": "skipped_estimated_footprint",
                        "reason": (
                            f"{impl} attention at S={seq}, sp={sp} holds "
                            f"~{est / GIB:.0f} GiB of dense score tensors "
                            f"(B*H*S^2{'/P' if impl == 'ring' else ''} "
                            f"fp32 x residency {CP_RESIDENCY_FACTOR}) "
                            f"against the {CP_FOOTPRINT_CAP / GIB:.0f} GiB "
                            "cap on this 125 GiB host simulating the "
                            "mesh in one RAM pool"
                        ),
                        "estimated_bytes": est,
                        "cap_bytes": CP_FOOTPRINT_CAP,
                    }, str(path))
                    continue
                log(f"  {name}")
                config = {
                    "experiment": {"name": name},
                    "model": dict(CP_SCALING_MODEL, **{"attention": impl}),
                    "parallelism": {"world_size": 1, "data_parallel": 1,
                                    "sequence_parallel": sp},
                    "input": {"batch_size": 1, "sequence_length": seq,
                              "seed": 42},
                    "execution": {
                        "warmup_iterations": 1,
                        "benchmark_iterations":
                            CP_BENCH_ITERS.get(seq, 2),
                    },
                    "training": {"learning_rate": 1e-3},
                }
                run_train(config, zero_stage=0, output_dir=str(out))
    from dlbb_tpu.stats.parallelism_report import write_cp_scaling_report

    rows = write_cp_scaling_report(out, STATS / "parallelism")
    log(f"  CP scaling: {len(rows)} (S, sp) cells "
        "(stats/parallelism/CP_SCALING.md)")


def stage_13b() -> None:
    """Full-depth 13B (hidden 5120 x 40 layers, reference
    ``models.py:265-270``): the committed evidence that the largest
    reference model size actually runs under this framework's sharding.

    Two artifacts, scoped to what the hardware can honestly measure:

    - **Forward benchmark, full depth, Megatron TP=8** (``results/e2e``) —
      exact reference parity: ``run_mpi.py`` is a forward-pass benchmark
      and the reference NEVER trains 13B (its only backward pass is the
      2-layer toy in ``test/ccl.py``).  TP-sharded weights are consumed in
      place by the sharded matmuls, so the host simulating all 8 devices
      holds the 23.4 GiB parameters exactly once.
    - **Training at true 13B layer geometry** — driver dryrun phase 9
      (``__graft_entry__.py``): ZeRO-3 + remat at h=5120/40-head/ffn-20480
      with depth 2; layers are scanned, so the compiled per-layer program
      and shardings equal the 40-layer model's.

    A full-depth 13B *training* step exceeds this host: XLA CPU
    materialises fp32 copies of bf16 weight stacks for the backward
    matmuls (~6x parameter bytes peak, measured 130+ GiB OOM at 125 GiB;
    with swap the in-process collective rendezvous stuck-detector aborts
    instead).  See ``docs/13b_single_chip.md`` for the single-chip HBM
    arithmetic and the real-pod story."""
    from dlbb_tpu.bench.e2e import run_e2e

    log("13B full-depth forward benchmark (tp=8)")
    config = {
        "experiment": {"name": "13B_tp8_forward"},
        "model": {"size": "13B", "attention": "full"},
        "parallelism": {"world_size": 8},  # world_size IS the TP degree
        "input": {"batch_size": 2, "sequence_length": 64, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 3},
    }
    run_e2e(config, output_dir=str(RESULTS / "e2e"))


def stage_flagship() -> None:
    """The reference's flagship experiment config — the single experiment
    its E2E harness is built around (``/root/reference/config/
    baseline_config.yaml:1-34``, consumed at ``run_mpi.py:120``): 7B,
    world_size=4 (TP), batch 8, seq 512 — run on the simulated mesh with
    the model/parallelism/input blocks VERBATIM.  Only the execution block
    shrinks (warmup 1 / bench 2, recorded in the artifact's own config):
    the single host core simulating all four ranks executes ~59 TFLOP per
    forward at tens of GFLOP/s, so the reference's 5+10 iterations would
    measure nothing extra for 6x the wall time."""
    from dlbb_tpu.bench.e2e import run_e2e
    from dlbb_tpu.utils.config import load_config

    log("flagship: baseline_config.yaml verbatim (7B, world_size=4)")
    config = load_config(str(REPO / "dlbb_tpu" / "configs"
                             / "baseline_config.yaml"))
    config["execution"] = {"warmup_iterations": 1,
                           "benchmark_iterations": 2}
    run_e2e(config, output_dir=str(RESULTS / "e2e"))


def stage_tpladder() -> None:
    """TP-scaling ladder: 1B, reference input shape (b8/s512), world_size
    (= TP degree) 1/2/4/8 on the simulated mesh — the committed evidence
    of how the Megatron sharding scales the flagship workload across the
    mesh axis (VERDICT r3 ask #2)."""
    from dlbb_tpu.bench.e2e import run_e2e

    for world in (1, 2, 4, 8):
        log(f"tp ladder: 1B world_size={world}")
        config = {
            "experiment": {"name": f"1b_simplified_s512_tp{world}_sim"},
            "model": {"size": "1B", "attention": "simplified"},
            "parallelism": {"world_size": world, "data_parallel": 1},
            "input": {"batch_size": 8, "sequence_length": 512, "seed": 42},
            "execution": {"warmup_iterations": 1,
                          "benchmark_iterations": 2},
        }
        run_e2e(config, output_dir=str(RESULTS / "e2e"))


def stage_multichip() -> None:
    """The headline bench.py multi-chip branch (BASELINE.json metric), run
    on the simulated 8-device mesh so the artifact exists even though the
    TPU image has one chip.  The JSON line is exactly what bench.py would
    print with >= 2 accelerator devices."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    log("multichip headline (8-rank simulated mesh)")
    out = bench.bench_allreduce_multichip(8)
    out["host"] = "cpu-simulated 8-device mesh (host-RAM bandwidth, not ICI)"
    dest = RESULTS / "multichip" / "bench_allreduce_multichip_8ranks.json"
    dest.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(json.dumps(out, indent=2) + "\n", dest)
    log(f"  {out['value']} {out['unit']} "
        f"(vs oneCCL baseline x{out['vs_baseline']})")


def stage_stats() -> None:
    from dlbb_tpu.stats import process_1d_results, process_3d_results

    log("stats: 1d")
    process_1d_results(RESULTS / "1d" / "xla_tpu", STATS / "1d" / "xla_tpu",
                       verbose=False)
    log("stats: 3d")
    process_3d_results(RESULTS / "3d" / "xla_tpu", STATS / "3d" / "xla_tpu",
                       implementation="xla_tpu", verbose=False)
    log("stats: variants")
    for name in sorted({*EXECUTABLE_VARIANTS, *VARIANTS_16}):
        impl = _impl(name)
        in_dir = RESULTS / "variants" / impl
        if in_dir.exists():
            process_1d_results(in_dir, STATS / "variants" / impl,
                               verbose=False)
    log("stats: variants3d")
    # every variant with 3D rows: the two full-grid winners, the whole
    # executable matrix from the tuning-grid stage, and the
    # 16-rank-shaped variants from its 16-rank rung
    for name in sorted({*VARIANTS_3D, *EXECUTABLE_VARIANTS, *VARIANTS_16}):
        impl = _impl(name)
        in_dir = RESULTS / "variants3d" / impl
        if in_dir.exists():
            process_3d_results(in_dir, STATS / "variants3d" / impl,
                               implementation=impl, verbose=False)
    from dlbb_tpu.stats import write_variants_report

    summary = write_variants_report(STATS / "variants")
    for size, w in summary["winners"].items():
        vs = (f"{w['speedup_vs_default']}x vs default"
              if w["speedup_vs_default"] is not None else "no default row")
        log(f"  variants {size}: {w['winner']} ({vs})")
    from dlbb_tpu.stats.variants_report import write_variants3d_report

    # base-corpus CSV + out dir come from the library defaults, shared
    # with the `reports` CLI
    rows3d = write_variants3d_report(STATS / "variants3d")
    if rows3d:
        log(f"  variants3d: {len(rows3d)} joined configs "
            f"(stats/variants3d/VARIANTS3D.md)")
    from dlbb_tpu.stats.northstar import (
        default_stats_1d_csv,
        write_northstar_report,
    )

    ns = write_northstar_report(
        default_stats_1d_csv(STATS), STATS / "northstar",
    )
    if ns:
        log(f"  northstar: {sum(ns.values())} size rows across "
            f"{list(ns)} (stats/northstar/NORTHSTAR.md)")
    cp_dir = RESULTS / "parallelism" / "cp_scaling"
    if any(cp_dir.glob("train_ddp_cp_s*.json")):
        from dlbb_tpu.stats.parallelism_report import (
            write_cp_scaling_report,
        )

        cp_rows = write_cp_scaling_report(cp_dir, STATS / "parallelism")
        log(f"  cp_scaling: {len(cp_rows)} (S, sp) cells "
            "(stats/parallelism/CP_SCALING.md)")


def stage_compare() -> None:
    """Head-to-head vs the reference's own checked-in corpus
    (``dlbb_tpu/stats/compare.py``) — the evidence for match/beat/lose
    per config, committed under ``stats/compare/``."""
    from dlbb_tpu.stats import write_comparison

    log("compare: reference corpus vs repo corpus")
    summary = write_comparison(
        Path("/root/reference"),
        RESULTS / "1d" / "xla_tpu",
        RESULTS / "3d" / "xla_tpu",
        STATS / "compare",
        repo_root=REPO,
    )
    for dim in ("1d", "3d"):
        s = summary[dim]
        log(f"  {dim}: {s['configs']} configs — {s['beat']} beat, "
            f"{s['match']} match, {s['lose']} lose, "
            f"{s['not_comparable_simulated']} not_comparable(simulated)")


def stage_baseline() -> None:
    """Fill BASELINE.json's ``published`` section from the committed stats."""
    import csv

    baseline_path = REPO / "BASELINE.json"
    data = json.loads(baseline_path.read_text())
    published: dict = {
        "host": "single-core CPU, simulated XLA device mesh "
                "(xla_force_host_platform_device_count; 8 devices for the "
                "2/4/8-rank stages, 16/32/56 for the ranks-16/-32/-56 stages — "
                "each artifact records its own mesh_shape + system_info)",
        "note": "collective numbers are host-RAM bandwidth, not ICI; the "
                "TPU-chip numbers live in results/e2e + BENCH_r*.json",
        "artifacts": {
            "results_1d": (sorted(
                str(p.relative_to(REPO))
                for p in (RESULTS / "1d").rglob("*.json"))[:3] + ["..."]
                if (RESULTS / "1d").exists() else []),
            "stats_1d_csv": "stats/1d/xla_tpu/benchmark_statistics.csv",
            "stats_3d_dir": "stats/3d/xla_tpu/",
            "variants": sorted(
                p.name for p in (STATS / "variants").iterdir()
                if p.is_dir()) if (STATS / "variants").exists() else [],
        },
    }
    csv_path = STATS / "1d" / "xla_tpu" / "benchmark_statistics.csv"
    if csv_path.exists():
        with csv_path.open() as f:
            rows = list(csv.DictReader(f))
        pick = [r for r in rows
                if r.get("operation") == "allreduce"
                and r.get("data_size_name") == "16MB"]
        published["allreduce_16MB"] = [
            {k: r.get(k) for k in
             ("num_ranks", "dtype", "mean_time_us", "bandwidth_gbps")}
            for r in pick
        ]
    # BASELINE.json configs[0] is literally "allreduce, float32, 1 MB,
    # 2 ranks" — name its artifact so the driver metric's first config has
    # a direct pointer
    config1 = (RESULTS / "1d" / "xla_tpu"
               / "xla_tpu_allreduce_ranks2_1MB_fp32.json")
    if config1.exists():
        r = json.loads(config1.read_text())
        flat = [t for row in r["timings"] for t in row]
        published["north_star_config1"] = {
            "config": "allreduce, float32, 1MB label, 2 ranks",
            "artifact": str(config1.relative_to(REPO)),
            "mean_time_us": round(
                sum(flat) / len(flat) * 1e6, 3),
        }
    e2e_dir = RESULTS / "e2e"
    if e2e_dir.exists():
        e2e = {}
        for pth in sorted(e2e_dir.glob("*.json")):
            r = json.loads(pth.read_text())
            if r.get("status") == "infeasible":
                # capability-boundary artifacts (e.g. dense@8192) carry a
                # reason instead of numbers; never let a stale boundary
                # file shadow a fresh measured artifact of the same name
                e2e.setdefault(
                    r["experiment"]["name"],
                    {"status": "infeasible", "reason": r["reason"]},
                )
                continue
            # publish the MEASURED backend (system_info), not the label
            # run_e2e stamps on every artifact — the simulated-mesh rows
            # (e.g. 13B_tp8_forward) must not read as chip numbers
            sysinfo = r.get("system_info") or {}
            entry = {
                "tokens_per_second": round(r["tokens_per_second"], 1),
                "achieved_tflops_per_second": round(
                    r["achieved_tflops_per_second"], 2),
                "backend": sysinfo.get("backend", r.get("backend")),
            }
            if sysinfo.get("backend") == "cpu":
                entry["simulated"] = True
            e2e[r["experiment"]["name"]] = entry
        published["e2e_corpus"] = e2e
    for key, rel in (
        ("variants_report", STATS / "variants" / "variants_comparison.csv"),
        ("northstar_report", STATS / "northstar" / "NORTHSTAR.md"),
        ("variants3d_report", STATS / "variants3d" / "VARIANTS3D.md"),
        ("parallelism_report", STATS / "parallelism" / "PARALLELISM.md"),
        ("cp_scaling_report", STATS / "parallelism" / "CP_SCALING.md"),
        ("comparison_report", STATS / "compare" / "COMPARISON.md"),
    ):
        if rel.exists():
            published[key] = str(rel.relative_to(REPO))
    mc = RESULTS / "multichip" / "bench_allreduce_multichip_8ranks.json"
    if mc.exists():
        published["multichip_headline"] = json.loads(mc.read_text())
    train_dir = RESULTS / "train"
    if train_dir.exists():
        ladder = {}
        for p in sorted(train_dir.glob("train_*.json")):
            r = json.loads(p.read_text())
            if "rows" in r and "method" in r:
                # derived joins (train_attrib_decomposition.json) share
                # the prefix but are not ladder artifacts; anything else
                # missing experiment.name still fails loudly below
                continue
            name = r["experiment"]["name"]
            if r.get("status") == "infeasible":
                # capability boundaries (e.g. the no-remat rung) publish
                # their reason, never shadow a measured artifact
                ladder.setdefault(
                    name, {"status": "infeasible", "reason": r["reason"]}
                )
                continue
            entry = {
                "step_time_mean_s": r["step_time"]["mean"],
                "tokens_per_second": r["tokens_per_second"],
                "achieved_tflops_per_second":
                    r["achieved_tflops_per_second"],
            }
            if r.get("achieved_tflops_per_second_incl_recompute") is not None:
                entry["achieved_tflops_per_second_incl_recompute"] = (
                    r["achieved_tflops_per_second_incl_recompute"])
            sysinfo = r.get("system_info") or {}
            if sysinfo.get("backend") == "cpu":
                entry["simulated"] = True
            ladder[name] = entry
        published["train_zero_ladder"] = ladder
    data["published"] = published
    atomic_write_text(json.dumps(data, indent=2) + "\n", baseline_path)
    log("BASELINE.json published section updated")


STAGES = {
    "1d": stage_1d,
    "1dfp32": stage_1dfp32,
    "1dfp32_16": stage_1dfp32_16,
    "1dfp16": stage_1dfp16,
    "1dfp16_16": stage_1dfp16_16,
    "1dtail": stage_1dtail,
    "1dtail_16": stage_1dtail_16,
    "3d": stage_3d,
    "1d16": stage_1d16,
    "1d32": stage_1d32,
    "1d56": stage_1d56,
    "3d16": stage_3d16,
    "variants": stage_variants,
    "variants16": stage_variants16,
    "variants3d": stage_variants3d,
    "variants3d_tuning": stage_variants3d_tuning,
    "variants3d_tuning16": stage_variants3d_tuning16,
    "train": stage_train,
    "flagship": stage_flagship,
    "tpladder": stage_tpladder,
    "parallelism": stage_parallelism,
    "cp_scaling": stage_cp_scaling,
    "13b": stage_13b,
    "multichip": stage_multichip,
    "stats": stage_stats,
    "compare": stage_compare,
    "baseline": stage_baseline,
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="all",
                    choices=["all", *STAGES])
    ap.add_argument("--fresh", action="store_true",
                    help="re-measure every config even if its artifact "
                         "exists (use after changing measurement code)")
    args = ap.parse_args()
    if args.fresh:
        global RESUME
        RESUME = False
    t0 = time.time()
    names = list(STAGES) if args.stage == "all" else [args.stage]
    for name in names:
        t = time.time()
        STAGES[name]()
        log(f"stage {name} done in {time.time() - t:.0f}s")
    log(f"all done in {time.time() - t0:.0f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
