#!/usr/bin/env python
"""Publish the in-repo baseline artifact corpus.

The reference's §6 baseline IS its checked-in artifacts (~1,700 result/stats
files under ``collectives/1d/results+stats`` and ``collectives/3d/...``).
This driver produces the dlbb_tpu analogue and is the provenance record for
everything under ``results/`` and ``stats/``:

- ``results/1d/xla_tpu/``        canonical reference grid (8 ops x
  {1KB,64KB,1MB,16MB} x ranks {2,4,8}; 16/32 via the 1d16/1d32 stages) plus the extended
  {64MB,256MB,1GB} sizes of the north-star curve (BASELINE.json metric)
- ``results/3d/xla_tpu/``        reference 3D grid (5 ops x B x S x H x
  ranks {4,8}, ``collectives/3d/openmpi.py:19-31``)
- ``results/variants/<impl>/``   allreduce tuning matrix over the executable
  variants (mesh topology / axis order / hierarchical / fusion-off) — the
  analogue of the reference's ``dsccl_{ring,rabs,...}`` result dirs
  (``collectives/3d/launch_dsccl.sh:34-65``)
- ``results/train/``             ZeRO-ladder train benchmarks incl. the
  fusion on/off (combiner-passes) comparison
- ``stats/...``                  the stats pipelines run over all of the
  above (reference ``collectives/{1d,3d}/stats.py`` schema)

Everything runs on the CPU-simulated 8-device mesh (this image has one TPU
chip; collectives are degenerate on one device — SURVEY §4's
"multi-node without a cluster" model).  The host has ONE core, so the sweeps
are time-budgeted: per-config measurement is capped (``max_config_seconds``)
and iteration counts recorded in each artifact are the actual ones.  Configs
whose global footprint would not fit host RAM are skipped
(``max_global_bytes``), mirroring the reference's per-config error-skip.

Usage: python scripts/publish_baselines.py [--stage 1d|3d|variants|train|stats|baseline|all]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

# The simulated device count is a process-start property (XLA_FLAGS).  The
# default 8-device mesh covers the reference's {2,4,8} rank sweeps; the
# reference's HEADLINE rows are at 16 ranks (BASELINE.md: oneCCL allreduce
# "16MB" @ 16 ranks) and its rank axis extends through 32/56, so the
# ``1d16``/``3d16``/``1d32`` stages run in SEPARATE invocations with
# DLBB_PUBLISH_DEVICES=16 (or 32).
N_DEVICES = int(os.environ.get("DLBB_PUBLISH_DEVICES", "8"))
force_cpu_simulation(N_DEVICES)

from dlbb_tpu.bench.runner import (  # noqa: E402
    DATA_SIZES_1D,
    EXTENDED_DATA_SIZES_1D,
    Sweep1D,
    Sweep3D,
    run_sweep,
)

RESULTS = REPO / "results"
STATS = REPO / "stats"

# Sweeps resume by default: the publisher is time-budgeted and routinely
# interrupted, and one-JSON-per-config makes resumption natural (the
# reference resumes the same way, SURVEY §5.4).  ``--fresh`` re-measures
# everything — REQUIRED after changing measurement/timing code, otherwise a
# rerun would silently rebuild stats from the stale committed corpus.
RESUME = True

GIB = 2**30

# Executable variant matrix (the fusion/threshold XLA_FLAGS variants need a
# real pod launcher and are excluded — see dlbb_tpu/comm/variants.py).
# "nofuse" is also excluded here: disabling the collective-combiner passes
# is a null experiment on single-collective 1D programs (nothing to
# combine — variants.py admits this); its honest measurement is the train
# stage's fused/nofuse comparison over many-collective ZeRO steps.
EXECUTABLE_VARIANTS = (
    "default",
    "ring",
    "grid2x4",
    "grid4x2",
    "hier2x4",
    "hier4x2",
    "grid2x2x2",
    "hier2x2x2",
)

TRAIN_MODEL = {
    "hidden_size": 256,
    "num_layers": 4,
    "num_heads": 8,
    "ffn_intermediate": 1024,
    "attention": "full",
    "dtype": "float32",
}

NOFUSE_OPTIONS = {
    "xla_disable_hlo_passes":
        "all-reduce-combiner,all-gather-combiner,reduce-scatter-combiner",
}


def log(msg: str) -> None:
    print(f"[publish {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def stage_1d() -> None:
    log("1D canonical grid (+ extended sizes)")
    out = RESULTS / "1d" / "xla_tpu"
    ext_sizes = tuple(
        (k, v) for k, v in EXTENDED_DATA_SIZES_1D.items()
        if k not in DATA_SIZES_1D
    )
    run_sweep(Sweep1D(
        output_dir=str(out),
        max_config_seconds=20.0,
        max_global_bytes=24 * GIB,
        resume=RESUME,
    ))
    # extended sizes: fewer rank counts, tighter budget — the big-payload
    # tail of the north-star 1KB..1GB curve
    run_sweep(Sweep1D(
        data_sizes=ext_sizes,
        rank_counts=(4, 8),
        output_dir=str(out),
        max_config_seconds=15.0,
        # quadratic-footprint ops (allgather/gather/alltoall) at the big
        # labels would otherwise spend tens of minutes shuffling host RAM
        # on the single simulating core — informative about nothing; the
        # skip is logged and the absence is the honest artifact
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_3d() -> None:
    log("3D reference grid")
    run_sweep(Sweep3D(
        output_dir=str(RESULTS / "3d" / "xla_tpu"),
        max_config_seconds=8.0,
        # 4 GiB global-footprint cap: above it a single iteration on the
        # one simulating core takes minutes (rendezvous threads thrashing
        # host RAM) and the full reference grid would not finish in a day.
        # Skips are logged per config; the honest artifact for those rows
        # is their absence + the skip line, not a number measuring nothing
        # but swap behaviour.
        max_global_bytes=4 * GIB,
        resume=RESUME,
    ))


def _require_devices(n: int, stage: str) -> bool:
    if N_DEVICES < n:
        log(f"SKIP stage {stage}: needs DLBB_PUBLISH_DEVICES={n} "
            f"(have {N_DEVICES}) — rerun as "
            f"DLBB_PUBLISH_DEVICES={n} python scripts/publish_baselines.py "
            f"--stage {stage}")
        return False
    return True


def stage_1d16() -> None:
    """16-rank canonical 1D grid — the reference's HEADLINE rank count
    (BASELINE.md: every 1D headline row, e.g. oneCCL allreduce "16MB"
    4.94 ms / 23.29 GB/s, is at 16 ranks;
    ``collectives/1d/stats/dsccl/benchmark_statistics.csv:18``).  Runs in a
    separate 16-device invocation (DLBB_PUBLISH_DEVICES=16)."""
    if not _require_devices(16, "1d16"):
        return
    log("1D canonical grid @ 16 ranks (reference headline rank count)")
    run_sweep(Sweep1D(
        rank_counts=(16,),
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=15.0,
        max_global_bytes=24 * GIB,
        resume=RESUME,
    ))


def stage_1d32() -> None:
    """32-rank canonical 1D grid — the reference's 1D rank axis extends
    through 32 and 56 ranks (``collectives/1d/openmpi.py:20``); 32 is the
    largest power-of-two rung this host can simulate in reasonable time.
    Runs in a DLBB_PUBLISH_DEVICES=32 invocation."""
    if not _require_devices(32, "1d32"):
        return
    log("1D canonical grid @ 32 ranks")
    run_sweep(Sweep1D(
        rank_counts=(32,),
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=10.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_1d56() -> None:
    """56-rank canonical 1D grid — the LAST rung of the reference's rank
    axis (its 56-core node's full width, ``collectives/1d/openmpi.py:20``).
    With this stage the corpus covers every reference 1D rank count
    {2,4,8,16,32,56}.  Runs in a DLBB_PUBLISH_DEVICES=56 invocation."""
    if not _require_devices(56, "1d56"):
        return
    log("1D canonical grid @ 56 ranks (full reference rank axis)")
    run_sweep(Sweep1D(
        rank_counts=(56,),
        output_dir=str(RESULTS / "1d" / "xla_tpu"),
        max_config_seconds=10.0,
        max_global_bytes=8 * GIB,
        resume=RESUME,
    ))


def stage_3d16() -> None:
    """16-rank 3D grid, all 5 ops — the reference sweeps 3D at ranks
    {4,8,16} (``collectives/3d/openmpi.py:19``); with this stage the 3D
    corpus covers the full reference rank axis."""
    if not _require_devices(16, "3d16"):
        return
    log("3D grid @ 16 ranks (all 5 ops)")
    run_sweep(Sweep3D(
        rank_counts=(16,),
        output_dir=str(RESULTS / "3d" / "xla_tpu"),
        max_config_seconds=8.0,
        max_global_bytes=4 * GIB,
        resume=RESUME,
    ))


def stage_variants() -> None:
    log("allreduce variant matrix")
    for name in EXECUTABLE_VARIANTS:
        log(f"  variant {name}")
        run_sweep(Sweep1D(
            variant=name,
            operations=("allreduce",),
            output_dir=str(RESULTS / "variants" / _impl(name)),
            max_config_seconds=20.0,
            max_global_bytes=24 * GIB,
            resume=RESUME,
        ))


def _impl(variant: str) -> str:
    return "xla_tpu" if variant == "default" else f"xla_tpu_{variant}"


def stage_train() -> None:
    from dlbb_tpu.train.loop import run_train

    out = RESULTS / "train"
    for stage in (0, 1, 2, 3):
        for fusion in (True, False) if stage in (0, 3) else ((True,)):
            execution = {"warmup_iterations": 2, "benchmark_iterations": 10}
            suffix = "fused"
            if not fusion:
                execution["compiler_options"] = dict(NOFUSE_OPTIONS)
                suffix = "nofuse"
            name = f"zero{stage}_dp8_{suffix}"
            log(f"  train {name}")
            config = {
                "experiment": {"name": name},
                "model": dict(TRAIN_MODEL),
                "parallelism": {"world_size": 1, "data_parallel": 8},
                "input": {"batch_size": 16, "sequence_length": 64,
                          "seed": 42},
                "execution": execution,
                "training": {"learning_rate": 1e-3},
            }
            run_train(config, zero_stage=stage, output_dir=str(out))


def stage_13b() -> None:
    """Full-depth 13B (hidden 5120 x 40 layers, reference
    ``models.py:265-270``): the committed evidence that the largest
    reference model size actually runs under this framework's sharding.

    Two artifacts, scoped to what the hardware can honestly measure:

    - **Forward benchmark, full depth, Megatron TP=8** (``results/e2e``) —
      exact reference parity: ``run_mpi.py`` is a forward-pass benchmark
      and the reference NEVER trains 13B (its only backward pass is the
      2-layer toy in ``test/ccl.py``).  TP-sharded weights are consumed in
      place by the sharded matmuls, so the host simulating all 8 devices
      holds the 23.4 GiB parameters exactly once.
    - **Training at true 13B layer geometry** — driver dryrun phase 9
      (``__graft_entry__.py``): ZeRO-3 + remat at h=5120/40-head/ffn-20480
      with depth 2; layers are scanned, so the compiled per-layer program
      and shardings equal the 40-layer model's.

    A full-depth 13B *training* step exceeds this host: XLA CPU
    materialises fp32 copies of bf16 weight stacks for the backward
    matmuls (~6x parameter bytes peak, measured 130+ GiB OOM at 125 GiB;
    with swap the in-process collective rendezvous stuck-detector aborts
    instead).  See ``docs/13b_single_chip.md`` for the single-chip HBM
    arithmetic and the real-pod story."""
    from dlbb_tpu.bench.e2e import run_e2e

    log("13B full-depth forward benchmark (tp=8)")
    config = {
        "experiment": {"name": "13B_tp8_forward"},
        "model": {"size": "13B", "attention": "full"},
        "parallelism": {"world_size": 8},  # world_size IS the TP degree
        "input": {"batch_size": 2, "sequence_length": 64, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 3},
    }
    run_e2e(config, output_dir=str(RESULTS / "e2e"))


def stage_multichip() -> None:
    """The headline bench.py multi-chip branch (BASELINE.json metric), run
    on the simulated 8-device mesh so the artifact exists even though the
    TPU image has one chip.  The JSON line is exactly what bench.py would
    print with >= 2 accelerator devices."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    log("multichip headline (8-rank simulated mesh)")
    out = bench.bench_allreduce_multichip(8)
    out["host"] = "cpu-simulated 8-device mesh (host-RAM bandwidth, not ICI)"
    dest = RESULTS / "multichip" / "bench_allreduce_multichip_8ranks.json"
    dest.parent.mkdir(parents=True, exist_ok=True)
    dest.write_text(json.dumps(out, indent=2) + "\n")
    log(f"  {out['value']} {out['unit']} "
        f"(vs oneCCL baseline x{out['vs_baseline']})")


def stage_stats() -> None:
    from dlbb_tpu.stats import process_1d_results, process_3d_results

    log("stats: 1d")
    process_1d_results(RESULTS / "1d" / "xla_tpu", STATS / "1d" / "xla_tpu",
                       verbose=False)
    log("stats: 3d")
    process_3d_results(RESULTS / "3d" / "xla_tpu", STATS / "3d" / "xla_tpu",
                       implementation="xla_tpu", verbose=False)
    log("stats: variants")
    for name in EXECUTABLE_VARIANTS:
        impl = _impl(name)
        in_dir = RESULTS / "variants" / impl
        if in_dir.exists():
            process_1d_results(in_dir, STATS / "variants" / impl,
                               verbose=False)
    from dlbb_tpu.stats import write_variants_report

    summary = write_variants_report(STATS / "variants")
    for size, w in summary["winners"].items():
        vs = (f"{w['speedup_vs_default']}x vs default"
              if w["speedup_vs_default"] is not None else "no default row")
        log(f"  variants {size}: {w['winner']} ({vs})")


def stage_compare() -> None:
    """Head-to-head vs the reference's own checked-in corpus
    (``dlbb_tpu/stats/compare.py``) — the evidence for match/beat/lose
    per config, committed under ``stats/compare/``."""
    from dlbb_tpu.stats import write_comparison

    log("compare: reference corpus vs repo corpus")
    summary = write_comparison(
        Path("/root/reference"),
        RESULTS / "1d" / "xla_tpu",
        RESULTS / "3d" / "xla_tpu",
        STATS / "compare",
        repo_root=REPO,
    )
    for dim in ("1d", "3d"):
        s = summary[dim]
        log(f"  {dim}: {s['configs']} configs — {s['beat']} beat, "
            f"{s['match']} match, {s['lose']} lose, "
            f"{s['not_comparable_simulated']} not_comparable(simulated)")


def stage_baseline() -> None:
    """Fill BASELINE.json's ``published`` section from the committed stats."""
    import csv

    baseline_path = REPO / "BASELINE.json"
    data = json.loads(baseline_path.read_text())
    published: dict = {
        "host": "single-core CPU, simulated XLA device mesh "
                "(xla_force_host_platform_device_count; 8 devices for the "
                "2/4/8-rank stages, 16/32/56 for the ranks-16/-32/-56 stages — "
                "each artifact records its own mesh_shape + system_info)",
        "note": "collective numbers are host-RAM bandwidth, not ICI; the "
                "TPU-chip numbers live in results/e2e + BENCH_r*.json",
        "artifacts": {
            "results_1d": (sorted(
                str(p.relative_to(REPO))
                for p in (RESULTS / "1d").rglob("*.json"))[:3] + ["..."]
                if (RESULTS / "1d").exists() else []),
            "stats_1d_csv": "stats/1d/xla_tpu/benchmark_statistics.csv",
            "stats_3d_dir": "stats/3d/xla_tpu/",
            "variants": sorted(
                p.name for p in (STATS / "variants").iterdir()
                if p.is_dir()) if (STATS / "variants").exists() else [],
        },
    }
    csv_path = STATS / "1d" / "xla_tpu" / "benchmark_statistics.csv"
    if csv_path.exists():
        with csv_path.open() as f:
            rows = list(csv.DictReader(f))
        pick = [r for r in rows
                if r.get("operation") == "allreduce"
                and r.get("data_size_name") == "16MB"]
        published["allreduce_16MB"] = [
            {k: r.get(k) for k in
             ("num_ranks", "mean_time_us", "bandwidth_gbps")}
            for r in pick
        ]
    e2e_dir = RESULTS / "e2e"
    if e2e_dir.exists():
        e2e = {}
        for pth in sorted(e2e_dir.glob("*.json")):
            r = json.loads(pth.read_text())
            if r.get("status") == "infeasible":
                # capability-boundary artifacts (e.g. dense@8192) carry a
                # reason instead of numbers; never let a stale boundary
                # file shadow a fresh measured artifact of the same name
                e2e.setdefault(
                    r["experiment"]["name"],
                    {"status": "infeasible", "reason": r["reason"]},
                )
                continue
            # publish the MEASURED backend (system_info), not the label
            # run_e2e stamps on every artifact — the simulated-mesh rows
            # (e.g. 13B_tp8_forward) must not read as chip numbers
            sysinfo = r.get("system_info", {})
            entry = {
                "tokens_per_second": round(r["tokens_per_second"], 1),
                "achieved_tflops_per_second": round(
                    r["achieved_tflops_per_second"], 2),
                "backend": sysinfo.get("backend", r.get("backend")),
            }
            if sysinfo.get("backend") == "cpu":
                entry["simulated"] = True
            e2e[r["experiment"]["name"]] = entry
        published["e2e_corpus"] = e2e
    vr = STATS / "variants" / "variants_comparison.csv"
    if vr.exists():
        published["variants_report"] = str(vr.relative_to(REPO))
    mc = RESULTS / "multichip" / "bench_allreduce_multichip_8ranks.json"
    if mc.exists():
        published["multichip_headline"] = json.loads(mc.read_text())
    train_dir = RESULTS / "train"
    if train_dir.exists():
        ladder = {}
        for p in sorted(train_dir.glob("train_*.json")):
            r = json.loads(p.read_text())
            ladder[r["experiment"]["name"]] = {
                "step_time_mean_s": r["step_time"]["mean"],
                "tokens_per_second": r["tokens_per_second"],
                "achieved_tflops_per_second":
                    r["achieved_tflops_per_second"],
            }
        published["train_zero_ladder"] = ladder
    data["published"] = published
    baseline_path.write_text(json.dumps(data, indent=2) + "\n")
    log("BASELINE.json published section updated")


STAGES = {
    "1d": stage_1d,
    "3d": stage_3d,
    "1d16": stage_1d16,
    "1d32": stage_1d32,
    "1d56": stage_1d56,
    "3d16": stage_3d16,
    "variants": stage_variants,
    "train": stage_train,
    "13b": stage_13b,
    "multichip": stage_multichip,
    "stats": stage_stats,
    "compare": stage_compare,
    "baseline": stage_baseline,
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="all",
                    choices=["all", *STAGES])
    ap.add_argument("--fresh", action="store_true",
                    help="re-measure every config even if its artifact "
                         "exists (use after changing measurement code)")
    args = ap.parse_args()
    if args.fresh:
        global RESUME
        RESUME = False
    t0 = time.time()
    names = list(STAGES) if args.stage == "all" else [args.stage]
    for name in names:
        t = time.time()
        STAGES[name]()
        log(f"stage {name} done in {time.time() - t:.0f}s")
    log(f"all done in {time.time() - t0:.0f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
