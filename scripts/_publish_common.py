"""Shared parent-loop for the real-chip publisher scripts.

One subprocess per config (fresh HBM arena per measurement), one
boundary-handling contract: a config whose failure is expected AND whose
stderr matches a memory/compile signature gets a deterministic
``*_infeasible.json`` boundary artifact (and its stale measured artifact
is unlinked); a config that succeeds unlinks its stale boundary artifact;
every other failure fails the run.  Used by ``publish_tpu_e2e.py`` and
``publish_tpu_train.py`` — the contract is pinned by
``tests/test_publish_scripts.py`` against both.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Any, Callable, Iterable

# error signatures that qualify a failure as the memory boundary
BOUNDARY_SIGNATURES = ("RESOURCE_EXHAUSTED", "remote_compile", "Allocat")


def run_worker_matrix(
    script_path: str,
    items: Iterable[Any],
    only_str: Callable[[Any], str],
    artifact_name: Callable[[Any], str],
    expected_fail_ok: set,
    write_boundary: Callable[[Any, str, int, str], Path],
    output: str,
    iters: int,
    label: Callable[[Any], str] = str,
) -> int:
    """Run every item as a ``--only`` worker subprocess; returns the exit
    code for ``main()``."""
    import subprocess

    failures = []
    for item in items:
        cmd = [sys.executable, script_path, "--iters", str(iters),
               "--output", output, "--only", only_str(item)]
        r = subprocess.run(cmd, capture_output=True, text=True)
        sys.stdout.write(r.stdout)
        if r.returncode == 0:
            # a previously-infeasible config that now measures cleanly
            # must not leave a stale boundary artifact shadowing it
            stale = Path(output) / f"{artifact_name(item)}_infeasible.json"
            stale.unlink(missing_ok=True)
            continue
        err_lines = [l for l in r.stderr.splitlines() if l.strip()]
        observed = err_lines[-1] if err_lines else f"exit {r.returncode}"
        is_boundary = (
            item in expected_fail_ok
            and any(sig in r.stderr for sig in BOUNDARY_SIGNATURES)
        )
        if is_boundary:
            # a config that regressed to infeasible must not leave its
            # stale measured artifact shadowing the fresh boundary file
            stale = Path(output) / f"{artifact_name(item)}.json"
            stale.unlink(missing_ok=True)
            write_boundary(item, output, r.returncode, observed)
            print(f"EXPECTED-INFEASIBLE {label(item)} "
                  "(boundary artifact written)", flush=True)
            continue
        sys.stderr.write(r.stderr)
        print(f"FAILED {label(item)} (exit {r.returncode})", flush=True)
        failures.append(item)
    if failures:
        print(f"{len(failures)} config(s) failed: "
              f"{[label(f) for f in failures]}", flush=True)
        return 1
    print(f"artifacts in {output}", flush=True)
    return 0
