#!/usr/bin/env python
"""Shared-prefix / quantized-KV evidence: prefix cache vs full prefill.

Measures the serving engine's refcounted shared-prefix KV cache and the
int8 KV wire layout (docs/serving.md, "Prefix cache & quantized KV")
through the engine's own trace replay and writes ``BENCH_prefix.json``
at the repo root:

- **equivalence gate first** — every prefix-cached and int8-KV setting
  replays its bench trace with token capture on and is compared
  per-request against the no-sharing fp engine on the same trace; a
  gate failure aborts the bench before any number is published.  fp
  prefix attach must be BIT-EXACT (the donor blocks hold the same K/V
  the skipped prefill would recompute — any mismatch is a bug).  int8
  is gated within tolerance: at least ``INT8_MIN_IDENTICAL`` of the
  requests must be fully token-identical (one flipped argmax diverges
  the rest of that request's greedy feedback, so per-position rates
  are meaningless after the flip; the per-request identity fraction is
  the honest scalar, and it is published per row).
- **TTFT/goodput grid** — {prefix off, prefix on} x {fp, int8 KV} over
  TWO seeded shared-prefix traces (~85% and ~60% shared prompt
  tokens, both above the >=50%-shared bar the TTFT acceptance claim
  needs; the claim is made on the LOWER one).  TTFT is
  arrival-to-first-token (queueing included), so the
  prefix cache's skipped prefill chunks show up both directly (the
  attached request computes only its unmatched suffix) and through
  faster queue drain.  The acceptance bars — prefix-on TTFT p50 >=
  1.3x the prefix-off engine on the >=50%-shared trace, and int8
  admitting >= 1.8x resident requests under the SAME ``hbm_budget_gb``
  (static, priced by ``kv_cache_bytes_per_device`` — the formula the
  memory audit pins against the compiled decode carry) — are recorded
  as checked claims, not prose.

Methodology follows ``scripts/bench_serving.py``: one warmup replay per
engine absorbs compiles, settings are INTERLEAVED within each timed
repetition so host drift cancels, and medians of per-rep throughput are
reported with min/max spread.

On this image the mesh is CPU-simulated: prefill-chunk dispatches pay
host sync, which the attach path skips — the regime the prefix cache
targets — but the int8 rows pay the dequant/requant FLOPs at real CPU
cost rather than the bandwidth win a chip's HBM gives them, so the
int8 THROUGHPUT rows undersell; the capacity ratio is
regime-independent static arithmetic.  The chip row stays keyed
``pending_tunnel`` for the next healthy tunnel window
(``DLBB_TPU_TESTS=1 python scripts/bench_prefix.py --chip``).

Usage: python scripts/bench_prefix.py [--requests N] [--reps R] [--chip]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from dlbb_tpu.utils.config import atomic_write_text  # noqa: E402

CHIP = "--chip" in sys.argv[1:]
if not CHIP:
    from dlbb_tpu.utils.simulate import force_cpu_simulation  # noqa: E402

    force_cpu_simulation(8)

import jax  # noqa: E402

from dlbb_tpu.comm.mesh import build_parallelism_mesh  # noqa: E402
from dlbb_tpu.models.configs import (  # noqa: E402
    ModelConfig,
    kv_cache_bytes_per_device,
)
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine  # noqa: E402
from dlbb_tpu.serve.traffic import generate_trace  # noqa: E402
from dlbb_tpu.stats.serving_report import write_prefix_report  # noqa: E402
from dlbb_tpu.utils.simulate import topology_record  # noqa: E402

# prefix attach requires dp=1 (the donor->slot copy is shard-local);
# tp=4 keeps the collective geometry the prefix_attach audit target pins
MESH = dict(data_parallel=1, tensor_parallel=4)

SERVE = dict(max_batch=8, block_size=8, max_seq=160, queue_capacity=64,
             prefill_chunk=16, hbm_budget_gb=None)

BENCH_MODEL = dict(hidden_size=64, num_layers=2, num_heads=4,
                   ffn_intermediate=128, dtype="float32",
                   attention="full")

# two shared-prefix populations per trace (two "system prompts"):
# share80 attaches 64 of ~80 prompt tokens (8 full blocks), share60
# attaches 48 (6 full blocks) — both above the >=50%-shared bar the
# TTFT acceptance claim is made on (the LOWER one carries the claim)
TRACES = {
    "share80": dict(seed=11, prefix_groups=2, prefix_len=64),
    "share60": dict(seed=13, prefix_groups=2, prefix_len=48),
}
PROMPTS = (65, 96)
OUTPUTS = (16, 32)

MODES = {
    "off_none": dict(prefix_caching=False, kv_quantization="none"),
    "on_none": dict(prefix_caching=True, kv_quantization="none"),
    "on_int8": dict(prefix_caching=True, kv_quantization="int8"),
}
BASELINE_MODE = "off_none"
# int8 tolerance: fraction of requests whose completed sequences must
# be fully identical to the fp oracle's (greedy feedback diverges a
# whole request on one flipped argmax, so this is the honest unit)
INT8_MIN_IDENTICAL = 0.7
# static capacity comparison: ~1 MiB/device of KV budget — small enough
# that resident-request counts are tangible, and the RATIO is
# budget-independent (bytes/request is linear in max_batch)
CAPACITY_BUDGET_GB = 0.001
ACCEPT_TTFT = {"setting": "share60/on_none",
               "baseline": "share60/off_none", "min_speedup": 1.3}
ACCEPT_CAPACITY = {"min_ratio": 1.8}


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _bench_trace(num_requests: int, *, seed: int, prefix_groups: int,
                 prefix_len: int):
    """Burst-ish poisson so the batch fills in one wave and the queue
    backs up — TTFT then prices both the attached request's shorter
    prefill and the faster drain of everyone behind it."""
    return generate_trace(
        "poisson", num_requests, seed=seed, rate=500.0,
        prompt_range=PROMPTS, output_range=OUTPUTS,
        prefix_groups=prefix_groups, prefix_len=prefix_len)


def _shared_share(trace) -> float:
    total = sum(r.prompt_len for r in trace.requests)
    shared = sum(r.prefix_len or 0 for r in trace.requests)
    return shared / total if total else 0.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16,
                    help="requests per replayed trace (default 16 = "
                         "two admission waves at max_batch=8)")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per setting (default 3)")
    ap.add_argument("--chip", action="store_true",
                    help="run on the real TPU chip instead of the "
                         "simulated mesh (fills the chip row)")
    ap.add_argument("--output", default=str(REPO / "BENCH_prefix.json"))
    args = ap.parse_args()

    model_cfg = ModelConfig.from_dict(BENCH_MODEL)
    mesh = build_parallelism_mesh(**MESH)
    traces = {
        name: _bench_trace(args.requests, **kw)
        for name, kw in TRACES.items()
    }

    # equivalence gate FIRST, on the published traces, with dedicated
    # capture engines (token capture syncs every step, so the timed
    # engines below run with it off): every prefix-cached / int8
    # setting must match the no-sharing fp engine's completed sequences
    def _captured_tokens(trace, extra):
        eng = ServingEngine(
            model_cfg, ServingConfig(**SERVE, **extra), mesh,
            verbose=False, capture_tokens=True)
        return eng.run_trace(trace)["completed_tokens"]

    identity = {}
    n_tok = 0
    for tname, trace in traces.items():
        oracle = _captured_tokens(trace, MODES[BASELINE_MODE])
        n_tok += sum(len(v) for v in oracle.values())
        for mname, extra in MODES.items():
            if mname == BASELINE_MODE:
                continue
            got = _captured_tokens(trace, extra)
            same = sum(1 for rid in oracle if got.get(rid) == oracle[rid])
            frac = same / len(oracle) if oracle else 1.0
            exact_required = extra["kv_quantization"] == "none"
            identity[f"{tname}/{mname}"] = {
                "exact": got == oracle,
                "identical_requests": same,
                "requests": len(oracle),
                "fraction": round(frac, 4),
                "gate": ("exact" if exact_required
                         else f">={INT8_MIN_IDENTICAL}"),
                "passed": (got == oracle if exact_required
                           else frac >= INT8_MIN_IDENTICAL),
            }
    if not all(v["passed"] for v in identity.values()):
        bad = {n: f"{v['identical_requests']}/{v['requests']}"
               for n, v in sorted(identity.items()) if not v["passed"]}
        raise SystemExit(
            "equivalence gate FAILED: prefix-cached/int8 serving "
            f"diverged from the no-sharing fp engine beyond its gate "
            f"for {bad} (fp must be bit-exact; int8 needs >= "
            f"{INT8_MIN_IDENTICAL} of requests identical) — refusing "
            "to publish throughput for a wrong result"
        )
    for name, v in sorted(identity.items()):
        print(f"[equivalence] {name}: {v['identical_requests']}/"
              f"{v['requests']} requests identical "
              f"(gate {v['gate']}): OK")

    # timed engines: capture off, one untimed warmup replay each to
    # absorb compiles, then interleaved timed repetitions
    engines = {
        f"{tname}/{mname}": (tname, ServingEngine(
            model_cfg, ServingConfig(**SERVE, **extra), mesh,
            verbose=False))
        for tname in traces
        for mname, extra in MODES.items()
    }
    for tname, eng in engines.values():
        eng.run_trace(traces[tname])
    per_rep: dict[str, list[dict]] = {name: [] for name in engines}
    for _ in range(args.reps):
        for name, (tname, eng) in engines.items():
            t0 = time.perf_counter()
            report = eng.run_trace(traces[tname])
            wall = time.perf_counter() - t0
            pre = report.get("prefix", {})
            per_rep[name].append({
                "tok_s": report["completed_output_tokens"] / wall,
                "ttft_p50_s": report["ttft"]["median"],
                "per_token_p50_s": report["per_token_latency"]["median"],
                "prefix_hits": pre.get("hits", 0),
                "hit_rate": pre.get("hit_rate"),
                "tokens_reused": pre.get("tokens_reused", 0),
            })

    settings_out = {}
    for name, (tname, _) in engines.items():
        mname = name.split("/", 1)[1]
        extra = MODES[mname]
        reps = per_rep[name]
        tok = [r["tok_s"] for r in reps]
        hr = [r["hit_rate"] for r in reps if r["hit_rate"] is not None]
        ident = identity.get(name)
        settings_out[name] = {
            "trace": tname,
            "prefix_caching": extra["prefix_caching"],
            "kv_quantization": extra["kv_quantization"],
            "output_tokens_per_s": {
                "median": _median(tok), "min": min(tok), "max": max(tok),
                "reps": tok,
            },
            "ttft_p50_ms": round(
                _median([r["ttft_p50_s"] for r in reps]) * 1e3, 3),
            "per_token_p50_ms": round(
                _median([r["per_token_p50_s"] for r in reps]) * 1e3, 3),
            "prefix_hits": _median([r["prefix_hits"] for r in reps]),
            "prefix_hit_rate": (round(_median(hr), 4) if hr else None),
            "tokens_reused": _median(
                [r["tokens_reused"] for r in reps]),
            "token_identical": None if ident is None else ident["exact"],
            "token_identity_fraction": (None if ident is None
                                        else ident["fraction"]),
        }
    for name in settings_out:
        tname = settings_out[name]["trace"]
        base_name = f"{tname}/{BASELINE_MODE}"
        base = settings_out[base_name]
        s = settings_out[name]
        s["baseline"] = base_name
        s["ttft_speedup_vs_baseline"] = round(
            base["ttft_p50_ms"] / s["ttft_p50_ms"], 3)
        s["goodput_speedup_vs_baseline"] = round(
            s["output_tokens_per_s"]["median"]
            / base["output_tokens_per_s"]["median"], 3)

    # static capacity: resident requests admissible under the SAME
    # budget, priced by the audited footprint formula (one request =
    # max_batch=1 slice; bytes are linear in max_batch)
    budget = int(CAPACITY_BUDGET_GB * 2**30)
    per_req = {
        kv: kv_cache_bytes_per_device(
            model_cfg, 1, SERVE["max_seq"],
            dp=MESH["data_parallel"], tp=MESH["tensor_parallel"],
            kv_quantization=kv, block_size=SERVE["block_size"])
        for kv in ("none", "int8")
    }
    resident = {kv: budget // b for kv, b in per_req.items()}
    cap_ratio = round(resident["int8"] / resident["none"], 3)
    capacity = {
        "hbm_budget_gb": CAPACITY_BUDGET_GB,
        "max_seq": SERVE["max_seq"],
        "block_size": SERVE["block_size"],
        "dp": MESH["data_parallel"],
        "tp": MESH["tensor_parallel"],
        "per_request_bytes_per_device": per_req,
        "resident_requests": resident,
        "capacity_ratio": cap_ratio,
        "min_ratio": ACCEPT_CAPACITY["min_ratio"],
        "passed": cap_ratio >= ACCEPT_CAPACITY["min_ratio"],
    }

    ttft_row = settings_out[ACCEPT_TTFT["setting"]]
    acceptance = {
        "ttft": {
            **ACCEPT_TTFT,
            "measured_speedup": ttft_row["ttft_speedup_vs_baseline"],
            "passed": (ttft_row["ttft_speedup_vs_baseline"]
                       >= ACCEPT_TTFT["min_speedup"]),
        },
        "capacity": {
            **ACCEPT_CAPACITY,
            "measured_ratio": cap_ratio,
            "passed": capacity["passed"],
        },
    }

    backend = jax.default_backend()
    payload = {
        "harness": "scripts/bench_prefix.py",
        "schema": "dlbb_bench_prefix_v1",
        "model": dict(BENCH_MODEL),
        "serving": dict(SERVE),
        "mesh": {"dp": MESH["data_parallel"],
                 "tp": MESH["tensor_parallel"]},
        "traces": {
            name: {
                "kind": trace.kind, "requests": len(trace),
                "seed": trace.seed,
                "prefix_groups": TRACES[name]["prefix_groups"],
                "prefix_len": TRACES[name]["prefix_len"],
                "prompt_range": list(PROMPTS),
                "output_range": list(OUTPUTS),
                "shared_token_share": round(_shared_share(trace), 4),
            }
            for name, trace in traces.items()
        },
        "repetitions": args.reps,
        "baseline": BASELINE_MODE,
        "methodology": (
            "identical seeded shared-prefix traces replayed through "
            "every engine; settings interleaved within each "
            "repetition; medians of per-rep completed-output-token "
            "throughput with min/max spread; completed-token identity "
            "gate (every prefix-cached / int8 setting == the "
            "no-sharing fp engine on the same trace) run on the "
            "published traces before any timing; capacity is static "
            "arithmetic over kv_cache_bytes_per_device, the formula "
            "the memory audit pins to the compiled decode carry"
        ),
        "backend": backend,
        "topology": topology_record(),
        "jax_version": jax.__version__,
        "host_cpu_count": os.cpu_count(),
        "timestamp": time.time(),
        "equivalence": {
            "checked": True,
            "oracle": f"{BASELINE_MODE} (per trace)",
            "int8_min_identical": INT8_MIN_IDENTICAL,
            "identical": dict(sorted(identity.items())),
            "tokens": n_tok,
        },
        "settings": settings_out,
        "capacity": capacity,
        "acceptance": acceptance,
        "claim": (
            "CPU-simulated mesh: every skipped prefill chunk saves a "
            "real host dispatch — the regime the attach path targets — "
            "but int8 pays dequant/requant at CPU FLOP cost with no "
            "HBM-bandwidth win, so int8 THROUGHPUT rows undersell; the "
            "capacity ratio is regime-independent."
            if backend == "cpu" else
            "chip run: walls are device-honest; the int8 rows see the "
            "HBM-bandwidth regime the quantized layout targets."
        ),
        "chip": (
            {"status": "measured", "backend": backend}
            if backend != "cpu" else {
                "status": "pending_tunnel",
                "note": ("chip rows keyed for the next healthy tunnel "
                         "window: DLBB_TPU_TESTS=1 python "
                         "scripts/bench_prefix.py --chip"),
            }
        ),
    }
    atomic_write_text(json.dumps(payload, indent=1) + "\n",
                      Path(args.output))
    write_prefix_report(Path(args.output), REPO / "stats" / "serving")
    for name, s in settings_out.items():
        tps = s["output_tokens_per_s"]
        hit = ("-" if s["prefix_hit_rate"] is None
               else f"{s['prefix_hit_rate']:.2f}")
        print(f"[{name:16s}] {tps['median']:8.1f} tok/s "
              f"({tps['min']:.1f}..{tps['max']:.1f})  "
              f"TTFT p50 {s['ttft_p50_ms']:8.1f} ms "
              f"x{s['ttft_speedup_vs_baseline']:.2f}, hit={hit}")
    ttft_acc = acceptance["ttft"]
    print(f"[acceptance] TTFT {ttft_acc['setting']} >= "
          f"{ttft_acc['min_speedup']}x vs {ttft_acc['baseline']}: "
          f"{'PASS' if ttft_acc['passed'] else 'FAIL'} "
          f"({ttft_acc['measured_speedup']:.2f}x)")
    print(f"[acceptance] int8 capacity >= "
          f"{ACCEPT_CAPACITY['min_ratio']}x residents: "
          f"{'PASS' if capacity['passed'] else 'FAIL'} "
          f"({cap_ratio:.2f}x: {resident['none']} fp -> "
          f"{resident['int8']} int8 under "
          f"{CAPACITY_BUDGET_GB} GB/device)")
    print(f"BENCH_prefix.json -> {args.output}")
    return 0 if (ttft_acc["passed"] and capacity["passed"]) else 1


if __name__ == "__main__":
    sys.exit(main())
