#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`` (single-chip
runs add an ``"extras"`` key with secondary 7B / full- and flash-attention
lines; the four headline keys are always present).

Two regimes, chosen by available device count:

- **>= 2 accelerator devices**: the reference's headline — 1D allreduce bus
  bandwidth at the "16MB" label (4,194,304 fp16/bf16 elements = 8 MiB), ring
  mesh over all devices.  ``vs_baseline`` is against the best reference
  backend (DeepSpeed+oneCCL, 23.29 GB/s @ 16 ranks —
  ``collectives/1d/stats/dsccl/benchmark_statistics.csv:18``, BASELINE.md).

- **1 device** (this image: one v5e chip; collectives are degenerate): the
  E2E TP-forward benchmark (reference ``run_mpi.py`` semantics) on the 1B
  model, tokens/s.  The reference publishes no E2E number (BASELINE.md), so
  the baseline is (re)established by running the reference's stack — torch
  CPU bf16, identical forward semantics, world 1 — on this host, cached in
  ``bench_baseline_cpu.json``.

All diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent
CPU_BASELINE_CACHE = REPO / "bench_baseline_cpu.json"

# DeepSpeed+oneCCL allreduce "16MB" @ 16 ranks (BASELINE.md)
ONECCL_BASELINE_GBPS = 23.29

E2E_BATCH, E2E_SEQ = 8, 512


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_allreduce_multichip(
    n: int,
    num_elements: int = 4_194_304,  # the reference's "16MB" label
    warmup: int = 10,
    iterations: int = 100,
) -> dict:
    import jax.numpy as jnp

    from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
    from dlbb_tpu.comm.ops import get_op, make_payload
    from dlbb_tpu.stats.stats1d import calculate_bandwidth
    from dlbb_tpu.utils.timing import time_collective

    mesh = build_mesh(MeshSpec.ring(n))
    op = get_op("allreduce")
    x = make_payload(op, mesh, ("ranks",), num_elements, dtype=jnp.bfloat16)
    fn = op.build(mesh, ("ranks",))
    timings, meta = time_collective(
        fn, x, chain=op.make_chain(n), warmup=warmup, iterations=iterations
    )
    max_t = max(timings)
    bw = calculate_bandwidth(num_elements, "bfloat16", max_t, "allreduce", n)
    # reference's 2x-off size label ("16MB" = 4,194,304 elements = 8 MiB)
    label = f"{num_elements * 4 / 2**20:g}MB"
    log(f"allreduce {label} x{n} ranks: max {max_t * 1e3:.3f} ms, "
        f"{bw:.2f} GB/s ({meta['timing_mode']})")
    return {
        "metric": f"1d_allreduce_{label}_bus_bandwidth_{n}ranks",
        "value": round(bw, 3),
        "unit": "GB/s",
        # from the PUBLISHED (rounded) value, so the artifact is
        # self-consistent: a consumer recomputing value/baseline must get
        # this number even when the raw bw sits on a rounding boundary
        "vs_baseline": round(round(bw, 3) / ONECCL_BASELINE_GBPS, 3),
        "timing_mode": meta["timing_mode"],
        "timing_granularity": meta.get("timing_granularity",
                                       "per_iteration"),
        "num_elements": num_elements,
        "max_time_s": max_t,
    }


def _cpu_baseline() -> dict:
    if CPU_BASELINE_CACHE.exists():
        cached = json.loads(CPU_BASELINE_CACHE.read_text())
        log(f"cpu baseline (cached): {cached['tokens_per_second']:.0f} tok/s")
        return cached
    log("measuring torch-CPU reference baseline (1B, bf16) ...")
    from dlbb_tpu.bench.torch_baseline import measure_torch_cpu_forward
    from dlbb_tpu.models.configs import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["1B"]
    result = measure_torch_cpu_forward(
        cfg.hidden_size, cfg.num_layers, cfg.ffn_intermediate,
        E2E_BATCH, E2E_SEQ,
    )
    CPU_BASELINE_CACHE.write_text(json.dumps(result, indent=2))
    log(f"cpu baseline (measured): {result['tokens_per_second']:.0f} tok/s")
    return result


def _e2e(size: str, attention: str, iters: int = 10,
         seq: int = E2E_SEQ) -> dict:
    from dlbb_tpu.bench.e2e import run_e2e

    config = {
        "experiment": {"name": f"bench_{size.lower()}_{attention}_s{seq}"
                               "_world1"},
        "model": {"size": size, "attention": attention},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": E2E_BATCH, "sequence_length": seq,
                  "seed": 42},
        "execution": {"warmup_iterations": 3, "benchmark_iterations": iters},
    }
    result = run_e2e(config, verbose=False)
    log(f"TPU {size}/{attention} forward: "
        f"{result['forward_time']['mean'] * 1e3:.2f} ms, "
        f"{result['tokens_per_second']:.0f} tok/s, "
        f"{result['achieved_tflops_per_second']:.1f} TFLOP/s "
        f"({result.get('timing_mode')})")
    return result


def bench_e2e_single_chip() -> dict:
    result = _e2e("1B", "simplified")
    tps = result["tokens_per_second"]
    baseline = _cpu_baseline()
    out = {
        "metric": "e2e_1B_forward_throughput_vs_reference_cpu_stack",
        "value": round(tps, 1),
        "unit": "tokens/s",
        # published-value consistency, as in bench_allreduce_multichip
        "vs_baseline": round(
            round(tps, 1) / baseline["tokens_per_second"], 3),
    }
    # secondary lines: the flagship 7B config and the real-attention 1B
    # paths at the reference's S=512, plus a full-vs-dense pair at S=1024
    # where the flash auto-route fires (FLASH_ROUTE_MIN_SEQ) so the
    # routing win is measured, not assumed.
    extras = {}
    for size, attention, seq in (
        ("7B", "simplified", E2E_SEQ), ("7B", "full", E2E_SEQ),
        ("1B", "full", E2E_SEQ), ("1B", "dense", E2E_SEQ),
        ("1B", "full", 1024), ("1B", "dense", 1024),
        ("1B", "flash", 8192),   # long-context headline (SURVEY §5.7)
    ):
        try:
            r = _e2e(size, attention, iters=10, seq=seq)
            key = (f"{size}_{attention}" if seq == E2E_SEQ
                   else f"{size}_{attention}_s{seq}")
            extras[key] = {
                "tokens_per_second": round(r["tokens_per_second"], 1),
                "achieved_tflops_per_second":
                    round(r["achieved_tflops_per_second"], 2),
                "forward_mean_ms":
                    round(r["forward_time"]["mean"] * 1e3, 3),
            }
        except Exception as e:  # noqa: BLE001 — extras never kill the headline
            log(f"extra bench {size}/{attention} failed: {e}")
    # train-side headline: one real fwd+bwd+optimizer step on the chip with
    # the reference's optimizer (memory-reduced Adam — bf16 moments, the
    # config that fits 16 GiB HBM; numerics vs fp32 Adam asserted in
    # tests/test_optim.py) at the round-4 best remat policy.
    try:
        r = _train_step_bench()
        extras["1B_train_adam_bf16m"] = {
            "tokens_per_second": round(r["tokens_per_second"], 1),
            "achieved_tflops_per_second":
                round(r["achieved_tflops_per_second"], 2),
            "achieved_tflops_per_second_incl_recompute":
                round(r["achieved_tflops_per_second_incl_recompute"], 2),
            "step_mean_ms": round(r["step_time"]["mean"] * 1e3, 3),
            "remat_policy": r["remat_policy"],
        }
    except Exception as e:  # noqa: BLE001 — extras never kill the headline
        log(f"train bench failed: {e}")
    if extras:
        out["extras"] = extras
    return out


def _train_step_bench() -> dict:
    from dlbb_tpu.train.loop import run_train

    config = {
        "experiment": {"name": "bench_1b_train_adam_bf16m"},
        "model": {"size": "1B", "attention": "full", "remat": True,
                  "remat_policy": "dots"},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": E2E_BATCH, "sequence_length": E2E_SEQ,
                  "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 5},
        "training": {"learning_rate": 1e-4, "optimizer": "adam",
                     "moments_dtype": "bfloat16"},
    }
    r = run_train(config, zero_stage=0, verbose=False)
    log(f"TPU 1B train step (adam/bf16m, remat={r['remat_policy']}): "
        f"{r['step_time']['mean'] * 1e3:.2f} ms, "
        f"{r['tokens_per_second']:.0f} tok/s, "
        f"{r['achieved_tflops_per_second']:.1f} TFLOP/s model "
        f"({r['achieved_tflops_per_second_incl_recompute']:.1f} incl "
        "recompute)")
    return r


def latest_chip_probe() -> "str | None":
    """Repo-relative path of the newest committed chip-capture artifact
    (``results/bench_probe_r*.json``), or None if none exists.  Newest
    by parsed round number — lexicographic order would mis-sort an
    unpadded round name (r9 vs r10)."""
    import re

    def round_no(p) -> int:
        m = re.search(r"_r(\d+)", p.stem)
        return int(m.group(1)) if m else -1

    probes = sorted(REPO.glob("results/bench_probe_r*.json"),
                    key=lambda p: (round_no(p), p.name))
    return str(probes[-1].relative_to(REPO)) if probes else None


def probe_backend(timeout_s: float = 180.0):
    """Device-init probe in a SUBPROCESS with a timeout.

    The axon tunnel can be down-but-not-refusing, in which case
    ``jax.devices()`` blocks indefinitely IN-PROCESS (observed: a
    multi-hour outage where even a 2048-matmul probe hung) — the probe
    must therefore run out-of-process where it can be killed.  Returns
    ``(device_count, None)`` on success or ``(None, reason)`` when the
    backend is unreachable (the reason lands in the degraded marker)."""
    import subprocess
    import sys as _sys

    try:
        r = subprocess.run(
            [_sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        reason = (f"device-init probe timed out after {timeout_s:.0f}s "
                  "(tunnel down-but-not-refusing)")
        log(f"backend probe: {reason}")
        return None, reason
    if r.returncode != 0:
        tail = r.stderr.strip().splitlines()[-1:] or ["(no stderr)"]
        reason = (f"device-init probe exited {r.returncode}: {tail[0]}")
        log(f"backend probe: {reason}")
        return None, reason
    try:
        return int(r.stdout.strip().splitlines()[-1]), None
    except (ValueError, IndexError):
        reason = (f"device-init probe printed no device count "
                  f"(stdout {r.stdout!r:.80})")
        log(f"backend probe: {reason}")
        return None, reason


def main() -> int:
    n, fail_reason = probe_backend()
    if n is None:
        # Record an honest result rather than hanging the driver: the
        # 8-rank simulated-mesh allreduce (the same measurement
        # stage_multichip commits), marked as the degraded path.
        log("falling back to the CPU-simulated 8-rank mesh")
        from dlbb_tpu.utils.simulate import force_cpu_simulation

        # the reason makes the fallback a first-class degraded topology:
        # any sweep this process runs journals it and stamps it into
        # sweep_manifest.json (utils/simulate.topology_record)
        force_cpu_simulation(8, degraded_reason=(
            f"accelerator backend unreachable ({fail_reason})"))
        out = bench_allreduce_multichip(8)
        out["degraded"] = (
            f"accelerator backend unreachable ({fail_reason}); "
            "CPU-simulated 8-device mesh measured instead — host-RAM "
            "bandwidth, not ICI/HBM"
        )
        # point at the most recent committed chip capture (bench.py run
        # end-to-end on a healthy tunnel earlier in the round), so a
        # bench-day outage doesn't orphan the round's chip evidence
        probe_artifact = latest_chip_probe()
        if probe_artifact is not None:
            out["chip_probe_artifact"] = probe_artifact
        print(json.dumps(out), flush=True)
        return 0

    import jax

    devices = jax.devices()
    log(f"devices: {devices}")
    if len(devices) >= 2:
        out = bench_allreduce_multichip(len(devices))
    else:
        out = bench_e2e_single_chip()
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
